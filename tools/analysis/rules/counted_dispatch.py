"""counted-dispatch: every call of a jit-wrapped callable is reachable
only through the counted dispatch seams.

The launch-count campaign's whole accounting rests on ONE invariant:
device programs launch at the counted seams — ``ops/prep._dispatch``,
``ssz/device_htr._device_level``, ``chain/bls/mesh.mesh_launch``,
``models/batch_verify.device_batch_verify*`` — where the launch
counters, ``lodestar_device_launch_seconds`` telemetry, and the
launch-budget bench gates live. A jitted callable invoked anywhere else
launches a compiled program the ledger never sees: the dashboard's
launches-per-batch quotient lies, the budget tests pass while the real
schedule regresses, and the AOT-bundle plan (which needs dispatch sites
statically enumerable) silently loses a site.

Enforced as a reference-graph fixpoint over the whole package (the
PR 7 loop-confined checker's construction, widened cross-module through
explicit imports):

* A scope is DISCIPLINED when it is a seam function, a trace-time body
  (jit/vmap-decorated, or registered with a jax transform or a lax
  control-flow primitive — calls of jitted callables inside another
  trace are inlining, not dispatches), or a function whose every
  non-registration reference in the package comes from disciplined
  scopes. Module-level STORAGE of a callable (the ``_FieldOps``
  static-argument tables) is not a call and does not poison the
  fixpoint; module-level CALLS do.
* A call of a jit-wrapped callable (resolved by name through defs,
  aliases, and imports — including ``name = jax.jit(...)`` assignments,
  jit-wrapped lambdas, and stored-then-called aliases) from any
  UNdisciplined scope, or at module level, is a finding.

Dynamic dispatch (callables in dicts, ``getattr``) is invisible to the
name-level graph; such sites carry a pragma with the reason, which is
the documentation they need anyway.
"""

from __future__ import annotations

import ast
import fnmatch
from pathlib import Path

from ..core import Finding, Rule
from ._device import DeviceIndex, ModuleInfo, build_index, last_segment

_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


class _ModuleScan(ast.NodeVisitor):
    """One module's scopes, references, and jit-call sites."""

    def __init__(self, idx: DeviceIndex, mi: ModuleInfo):
        self.idx = idx
        self.mi = mi
        self.stack: list[ast.AST] = []
        #: id() of Name/Attribute nodes that are the callee of a Call
        self.callees: set[int] = set()
        #: (rel, name) -> [(scope node | None)] non-registration refs
        self.refs: dict[tuple[str, str], list[ast.AST | None]] = {}
        #: (call node, (rel, name), scope node | None)
        self.jit_calls: list[tuple[ast.Call, tuple[str, str], ast.AST | None]] = []
        #: lambda id -> lexically enclosing scope node
        self.lambda_parent: dict[int, ast.AST | None] = {}

    def scan(self) -> None:
        self.visit(self.mi.tree)

    # -- scope tracking --------------------------------------------------------

    def _enter(self, node: ast.AST) -> None:
        if isinstance(node, ast.Lambda):
            self.lambda_parent[id(node)] = self.stack[-1] if self.stack else None
        self.stack.append(node)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _enter
    visit_AsyncFunctionDef = _enter
    visit_Lambda = _enter

    # -- references and call sites --------------------------------------------

    def _current(self) -> ast.AST | None:
        return self.stack[-1] if self.stack else None

    def _note_ref(self, node: ast.AST) -> None:
        if id(node) in self.mi.registration_refs:
            return
        mi = self.mi
        target = self.idx.resolve(mi, node)
        if target is None and isinstance(node, ast.Attribute):
            # `self.method()` style: name-keyed, like the PR 7 checker
            if node.attr in mi.func_defs:
                target = (mi.rel, node.attr)
        if target is not None and self._known_function(target):
            if self._current() is None and id(node) not in self.callees:
                # module-level STORAGE (the _FieldOps static-argument
                # tables, __all__-adjacent aliases): storing a callable
                # is not calling it — record the symbol without
                # poisoning its fixpoint. Calls THROUGH the table are
                # dynamic dispatch, invisible to the name graph either
                # way; calls OF jitted names stay caught via aliases.
                self.refs.setdefault(target, [])
                return
            self.refs.setdefault(target, []).append(self._current())

    def _known_function(self, target: tuple[str, str]) -> bool:
        rel, name = target
        other = self.idx.modules.get(rel)
        return other is not None and (
            name in other.func_defs or name in other.jit_names
        )

    def visit_Name(self, node: ast.Name) -> None:
        self._note_ref(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        self._note_ref(node)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        self.callees.add(id(node.func))
        target = self.idx.resolve(self.mi, node.func)
        if target is not None and self._is_jitted(target):
            self.jit_calls.append((node, target, self._current()))
        self.generic_visit(node)

    def _is_jitted(self, target: tuple[str, str]) -> bool:
        rel, name = target
        return self.idx.is_jitted(rel, name)


class CountedDispatchRule(Rule):
    name = "counted-dispatch"
    description = (
        "jit-wrapped callables are only invoked through the counted "
        "dispatch seams (ops/prep._dispatch, ssz/device_htr._device_level, "
        "chain/bls/mesh.mesh_launch, models/batch_verify.device_batch_"
        "verify*) or inside trace-time bodies — uncounted launches are "
        "invisible to the launch ledger and budget gates"
    )
    scope = "project"

    def check_project(self, repo_root: Path, sources=None):
        idx = build_index(repo_root, sources)
        if idx is None:
            return []

        scans = {rel: _ModuleScan(idx, mi) for rel, mi in idx.modules.items()}
        for scan in scans.values():
            scan.scan()

        # disciplined fixpoint: roots are seam defs + trace-time bodies
        disciplined: set[int] = set()
        lambda_parent: dict[int, ast.AST | None] = {}
        for rel, mi in idx.modules.items():
            lambda_parent.update(scans[rel].lambda_parent)
            disciplined |= mi.trace_root_defs
            for glob in idx.seam_globs(rel):
                for name, fns in mi.func_defs.items():
                    if fnmatch.fnmatchcase(name, glob):
                        disciplined.update(id(fn) for fn in fns)

        def scope_ok(scope: ast.AST | None) -> bool:
            seen = 0
            while isinstance(scope, ast.Lambda):
                if id(scope) in disciplined:
                    return True
                scope = lambda_parent.get(id(scope))
                seen += 1
                if seen > 50:  # defensive: malformed parent chain
                    return False
            return scope is not None and id(scope) in disciplined

        refs: dict[tuple[str, str], list[ast.AST | None]] = {}
        for scan in scans.values():
            for target, sites in scan.refs.items():
                refs.setdefault(target, []).extend(sites)

        changed = True
        while changed:
            changed = False
            for (rel, name), sites in refs.items():
                fns = idx.modules[rel].func_defs.get(name, ())
                if not fns or all(id(fn) in disciplined for fn in fns):
                    continue
                if all(scope_ok(s) for s in sites):
                    disciplined.update(id(fn) for fn in fns)
                    changed = True

        seam_list = "ops/prep._dispatch, ssz/device_htr._device_level, " \
            "chain/bls/mesh.mesh_launch, models/batch_verify.device_batch_verify*"
        findings: list[Finding] = []
        for rel, scan in sorted(scans.items()):
            for call, (tgt_rel, tgt_name), scope in scan.jit_calls:
                if scope_ok(scope):
                    continue
                where = (
                    "at module level"
                    if scope is None
                    else f"in '{getattr(scope, 'name', '<lambda>')}'"
                )
                mod = tgt_rel.removesuffix(".py").replace("/", ".")
                findings.append(
                    Finding(
                        self.name,
                        str(repo_root / rel),
                        call.lineno,
                        f"uncounted device dispatch: jit-wrapped "
                        f"'{mod}.{tgt_name}' called {where}, which is not "
                        f"reachable only through the counted seams "
                        f"({seam_list}) — the launch is invisible to the "
                        "launch counters/telemetry and every launch-budget "
                        "gate; route it through a counted seam",
                    )
                )
        return findings
