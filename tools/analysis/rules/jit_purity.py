"""jit-purity: no host-sync or retrace hazards inside jit-traced bodies.

The staged-jit miscompile doctrine as a rule: a jitted body runs ONCE
per (shape, static-args) key as a trace, so host-side operations inside
it either force a device round trip mid-program or silently bake a
trace-time value into the compiled artifact:

* ``.item()`` — blocks on the device and syncs mid-trace; inside a
  traced body it also means the trace depends on a runtime VALUE, the
  exact bug class the fused-vs-unfused differential exists to catch.
* ``int(x)`` / ``float(x)`` / ``bool(x)`` on a traced parameter — a
  concretization: either a tracer error at first trace, or (through
  numpy coercion) a value frozen at trace time.
* ``np.*`` calls FED BY a traced parameter — host numpy inside a trace
  computes on trace-time values; a result depending on a traced
  argument is baked into the compiled program and is simply wrong for
  the next batch. (An np call fed only by constants or static/plain-
  Python parameters — the static-exponent bit tables — is a legal
  trace-time constant and stays quiet; parameters annotated
  ``int``/``float``/``bool``/``str`` are treated as trace-time.)
* ``if``/``while`` on a traced parameter — Python control flow
  branches on the TRACER, not the value: ConcretizationError at best, a
  trace specialized to the first batch at worst. (``is``/``is None``
  tests are trace-time identity and stay legal; parameters declared in
  ``static_argnums``/``static_argnames`` are Python values and exempt.)
* ``range(len(param))`` loops — unrolls the trace over a traced axis:
  a program whose SIZE depends on the batch, i.e. a compile per length.

Scope: functions decorated with / passed to ``jax.jit`` (including
``functools.partial(jax.jit, ...)`` and jit-wrapped lambdas) and their
statically-reachable same-module helpers. Helpers get the host-sync
checks (``.item()``, ``np.*``); the parameter-flow checks run only on
the jit roots themselves, where the static-argument declaration is
visible — a helper's plain-Python flag arguments (trace-time constants)
must not false-positive.
"""

from __future__ import annotations

import ast

from ..core import Finding, Rule, SourceFile
from ._device import is_jit_call, last_segment, static_params

_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _numpy_aliases(tree: ast.Module) -> set[str]:
    """Names bound to HOST numpy (``jax.numpy`` aliases excluded)."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    out.add(a.asname or "numpy")
    return out


def _jit_roots(tree: ast.Module) -> dict[int, tuple[ast.AST, set[str]]]:
    """id -> (def/lambda node, static param names) for every jit root."""
    defs: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, _SCOPES):
            defs.setdefault(node.name, node)

    roots: dict[int, tuple[ast.AST, set[str]]] = {}
    for node in ast.walk(tree):
        if isinstance(node, _SCOPES):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and is_jit_call(dec):
                    roots[id(node)] = (node, static_params(dec, node))
                elif last_segment(dec) == "jit":
                    roots[id(node)] = (node, set())
        elif isinstance(node, ast.Call) and is_jit_call(node):
            for arg in node.args[:1]:
                if isinstance(arg, ast.Lambda):
                    roots[id(arg)] = (arg, static_params(node, arg))
                elif isinstance(arg, ast.Name) and arg.id in defs:
                    fn = defs[arg.id]
                    roots.setdefault(id(fn), (fn, static_params(node, fn)))
                elif isinstance(arg, ast.Call):
                    # jax.jit(jax.vmap(f)): the innermost named callable
                    inner = arg.args[0] if arg.args else None
                    if isinstance(inner, ast.Name) and inner.id in defs:
                        fn = defs[inner.id]
                        roots.setdefault(id(fn), (fn, static_params(node, fn)))
    return roots


def _reachable_helpers(
    tree: ast.Module, roots: dict[int, tuple[ast.AST, set[str]]]
) -> list[ast.AST]:
    """Same-module defs referenced (transitively) from a jit root body."""
    defs: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, _SCOPES):
            defs.setdefault(node.name, node)
    seen: set[int] = set(roots)
    frontier = [fn for fn, _ in roots.values()]
    helpers: list[ast.AST] = []
    while frontier:
        scope = frontier.pop()
        for node in ast.walk(scope):
            if isinstance(node, ast.Name) and node.id in defs:
                fn = defs[node.id]
                if id(fn) not in seen:
                    seen.add(id(fn))
                    helpers.append(fn)
                    frontier.append(fn)
    return helpers


_PLAIN_ANNOTATIONS = {"int", "float", "bool", "str"}


def _dynamic_params(fn: ast.AST, statics: set[str]) -> set[str]:
    a = fn.args
    params = list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
    out: set[str] = set()
    for p in params:
        if p.arg in statics or p.arg in ("self", "cls"):
            continue
        ann = p.annotation
        if isinstance(ann, ast.Name) and ann.id in _PLAIN_ANNOTATIONS:
            continue  # `scalar: int` is a trace-time Python value
        out.add(p.arg)
    return out


def _bare_dyn_names(node: ast.AST, dyn: set[str]) -> list[ast.Name]:
    """Dynamic-param Names in `node`, skipping Attribute subtrees
    (``x.shape``/``x.ndim``/``x.dtype`` are trace-static)."""
    hits: list[ast.Name] = []

    def walk(n: ast.AST) -> None:
        if isinstance(n, ast.Attribute):
            return
        if isinstance(n, ast.Name) and n.id in dyn:
            hits.append(n)
        for child in ast.iter_child_nodes(n):
            walk(child)

    walk(node)
    return hits


class JitPurityRule(Rule):
    name = "jit-purity"
    description = (
        "no host-sync/retrace hazards inside jit-traced bodies: .item(), "
        "int()/float()/bool() on traced params, host np.* calls, Python "
        "if/while on traced params, range(len(param)) trace unrolling"
    )

    def check(self, sf: SourceFile):
        tree = sf.tree
        roots = _jit_roots(tree)
        if not roots:
            return []
        np_aliases = _numpy_aliases(tree)
        helpers = _reachable_helpers(tree, roots)
        findings: list[Finding] = []
        flagged: set[tuple[int, str]] = set()

        def flag(node: ast.AST, kind: str, msg: str) -> None:
            key = (node.lineno, kind)
            if key not in flagged:
                flagged.add(key)
                findings.append(Finding(self.name, sf.path, node.lineno, msg))

        def host_sync_checks(scope: ast.AST, where: str, dyn: set[str]) -> None:
            for node in ast.walk(scope):
                if node is not scope and isinstance(node, _SCOPES):
                    continue  # nested defs are visited as their own helpers
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr == "item":
                    flag(
                        node, "item",
                        f".item() inside {where} forces a device→host sync "
                        "mid-trace and bakes a runtime value into the "
                        "compiled program — keep the value on device or "
                        "hoist the read outside the jitted body",
                    )
                elif (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id in np_aliases
                    # constant/static-fed np (bit tables) is a legal
                    # trace-time constant; the hazard is a traced value
                    and any(_bare_dyn_names(a, dyn) for a in node.args)
                ):
                    flag(
                        node, "np",
                        f"host numpy call np.{f.attr}(...) inside {where} "
                        "is fed by a traced argument — the result is "
                        "frozen into the compiled program at trace time; "
                        "use jnp or hoist the computation out of the "
                        "traced body",
                    )

        def param_flow_checks(fn: ast.AST, statics: set[str]) -> None:
            dyn = _dynamic_params(fn, statics)
            if not dyn:
                return
            name = getattr(fn, "name", "<lambda>")

            for node in ast.walk(fn):
                if node is not fn and isinstance(node, _SCOPES):
                    continue
                if isinstance(node, ast.Call):
                    f = node.func
                    if (
                        isinstance(f, ast.Name)
                        and f.id in ("int", "float", "bool")
                        and len(node.args) == 1
                        and isinstance(node.args[0], ast.Name)
                        and node.args[0].id in dyn
                    ):
                        flag(
                            node, "cast",
                            f"{f.id}({node.args[0].id}) concretizes a traced "
                            f"parameter of jitted '{name}' — a tracer error "
                            "or a value frozen at trace time; compute on "
                            "device or pass it as a static argument",
                        )
                    elif (
                        isinstance(f, ast.Name)
                        and f.id == "range"
                        and any(
                            isinstance(a, ast.Call)
                            and isinstance(a.func, ast.Name)
                            and a.func.id == "len"
                            and a.args
                            and isinstance(a.args[0], ast.Name)
                            and a.args[0].id in dyn
                            for a in node.args
                        )
                    ):
                        flag(
                            node, "len-loop",
                            f"range(len(...)) over a traced parameter of "
                            f"jitted '{name}' unrolls the trace per batch "
                            "length — one compile per size; pad to the "
                            "shared pow-2 size classes or use lax control "
                            "flow",
                        )
                elif isinstance(node, (ast.If, ast.While)):
                    test = node.test
                    if isinstance(test, ast.Compare) and all(
                        isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
                    ):
                        continue  # `x is None` is trace-time identity
                    for hit in _bare_dyn_names(test, dyn):
                        flag(
                            node, "branch",
                            f"Python {'if' if isinstance(node, ast.If) else 'while'} "
                            f"on traced parameter '{hit.id}' of jitted "
                            f"'{name}' branches on the tracer, not the "
                            "value — use jnp.where/lax.cond, or declare "
                            "the parameter static",
                        )
                        break

        for fn, statics in roots.values():
            host_sync_checks(
                fn,
                f"jitted '{getattr(fn, 'name', '<lambda>')}'",
                _dynamic_params(fn, statics),
            )
            param_flow_checks(fn, statics)
        for fn in helpers:
            host_sync_checks(
                fn,
                f"'{fn.name}' (reached from a jitted body)",
                _dynamic_params(fn, set()),
            )
        return findings
