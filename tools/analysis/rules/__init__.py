"""Rule registry: one instance of every project checker."""

from __future__ import annotations

from .alert_wiring import AlertWiringRule
from .bench_wiring import BenchWiringRule
from .blocking_under_lock import BlockingUnderLockRule
from .counted_dispatch import CountedDispatchRule
from .degrade_count import DegradeAndCountRule
from .fail_closed import FailClosedVerdictsRule
from .fault_wiring import FaultWiringRule
from .jit_purity import JitPurityRule
from .lock_discipline import LockDisciplineRule
from .monotonic import MonotonicDurationsRule
from .pow2_dispatch import Pow2DispatchRule
from .rest_wiring import RestRouteWiringRule
from .span_discipline import SpanDisciplineRule
from .tuning_provenance import TuningProvenanceRule
from .wiring import MetricsCliWiringRule

ALL_RULES = (
    LockDisciplineRule(),
    BlockingUnderLockRule(),
    FailClosedVerdictsRule(),
    SpanDisciplineRule(),
    MonotonicDurationsRule(),
    MetricsCliWiringRule(),
    RestRouteWiringRule(),
    FaultWiringRule(),
    BenchWiringRule(),
    AlertWiringRule(),
    TuningProvenanceRule(),
    CountedDispatchRule(),
    JitPurityRule(),
    Pow2DispatchRule(),
    DegradeAndCountRule(),
)

RULES_BY_NAME = {r.name: r for r in ALL_RULES}

__all__ = ["ALL_RULES", "RULES_BY_NAME"]
