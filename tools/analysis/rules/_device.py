"""Shared device-dispatch index for the dispatch-discipline rules.

The four dispatch rules (counted-dispatch, jit-purity, pow2-dispatch,
degrade-and-count) all need the same facts about the tree: which names
are bound to jit-wrapped callables (decorator, ``name = jax.jit(...)``
assignment, lambda, alias), which function bodies are TRACE-TIME (a
call of a jitted callable inside another jitted body is inlining, not a
dispatch), how imports map local names onto other modules' functions,
and which functions are the counted seams. This module computes that
once per run — per-module ``ModuleInfo`` plus a cross-module
``DeviceIndex`` — reusing the parsed-AST cache ``analyze()`` hands to
project rules.

Resolution is by NAME through explicit imports (``from . import curve
as cv`` → ``cv.fold_sum``; ``from .hash import hash_nodes_cpu``),
including function-level imports. Dynamic storage (dicts of callables,
``getattr``) is invisible — the same naming-discipline approximation as
the PR 7 loop-confined checker, and the reason the rules stay
suppressible with a written reason.
"""

from __future__ import annotations

import ast
import fnmatch
from dataclasses import dataclass, field
from pathlib import Path

from ..core import SourceFile, cached_source, iter_py_files

#: The counted dispatch seams: every device launch must be reachable
#: only through these (repo-relative module path, function-name glob).
SEAMS = (
    ("lodestar_tpu/ops/prep.py", "_dispatch"),
    ("lodestar_tpu/ssz/device_htr.py", "_device_level"),
    ("lodestar_tpu/chain/bls/mesh.py", "mesh_launch"),
    ("lodestar_tpu/models/batch_verify.py", "device_batch_verify*"),
)

#: jax transforms whose callable arguments execute at TRACE time — a
#: function handed to one of these is a trace root, and the handoff
#: itself is a registration, not a call/dispatch. Includes the lax
#: control-flow primitives: a fori_loop/scan body runs as part of the
#: enclosing trace, not as its own dispatch.
_TRACE_WRAPPERS = {
    "jit",
    "vmap",
    "pmap",
    "shard_map",
    "grad",
    "value_and_grad",
    "checkpoint",
    "custom_jvp",
    "custom_vjp",
    "fori_loop",
    "while_loop",
    "scan",
    "cond",
    "switch",
    "associative_scan",
}


def last_segment(node: ast.AST) -> str | None:
    """Final dotted segment of a Name/Attribute expression."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, None for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _is_jit_expr(node: ast.AST) -> bool:
    """``jax.jit`` / ``jit`` as a bare reference."""
    return last_segment(node) == "jit"


def is_jit_call(call: ast.Call) -> bool:
    """``jax.jit(...)`` or ``functools.partial(jax.jit, ...)``."""
    seg = last_segment(call.func)
    if seg == "jit":
        return True
    return seg == "partial" and bool(call.args) and _is_jit_expr(call.args[0])


def is_trace_wrapper_call(call: ast.Call) -> bool:
    """A call whose callable arguments are trace-time registrations."""
    seg = last_segment(call.func)
    if seg in _TRACE_WRAPPERS:
        return True
    return seg == "partial" and bool(call.args) and (
        last_segment(call.args[0]) in _TRACE_WRAPPERS
    )


def _const_tuple(node: ast.AST) -> tuple:
    """Literal ints/strs out of a constant or tuple-of-constants."""
    if isinstance(node, ast.Constant):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            e.value for e in node.elts if isinstance(e, ast.Constant)
        )
    return ()


def _param_names(fn: ast.AST) -> list[str]:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        return []
    a = fn.args
    return [p.arg for p in list(a.posonlyargs) + list(a.args)]


def static_params(call: ast.Call, fn: ast.AST | None) -> set[str]:
    """Param names pinned static by ``static_argnums``/``static_argnames``
    keywords on a jit/partial call (positional indices need the wrapped
    function's signature)."""
    out: set[str] = set()
    names = _param_names(fn) if fn is not None else []
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            out.update(v for v in _const_tuple(kw.value) if isinstance(v, str))
        elif kw.arg == "static_argnums":
            for v in _const_tuple(kw.value):
                if isinstance(v, int) and 0 <= v < len(names):
                    out.add(names[v])
    return out


@dataclass
class ModuleInfo:
    """Per-module dispatch facts (see module docstring)."""

    rel: str  # posix path relative to repo root
    sf: SourceFile
    #: local name -> static param names, for every name bound to a
    #: jit-wrapped callable (decorated def, jit assignment, alias)
    jit_names: dict[str, set[str]] = field(default_factory=dict)
    #: id() of def/lambda nodes whose BODY runs at trace time (jit/vmap
    #: decorated, or registered with a trace wrapper)
    trace_root_defs: set[int] = field(default_factory=set)
    #: id() of Name/Attribute nodes that are wrapper registrations
    #: (``jax.jit(f)``'s f) — not references, not calls
    registration_refs: set[int] = field(default_factory=set)
    #: local alias -> other module's rel path (``from x import mod as m``)
    mod_alias: dict[str, str] = field(default_factory=dict)
    #: local alias -> (module rel path, symbol) for symbol imports
    sym_alias: dict[str, tuple[str, str]] = field(default_factory=dict)
    #: top-level-visible function defs by name (methods included — the
    #: reference graph is name-keyed, like the loop-confined checker)
    func_defs: dict[str, list[ast.AST]] = field(default_factory=dict)

    @property
    def tree(self) -> ast.Module:
        return self.sf.tree


def _module_rel(base_parts: list[str], files: set[str]) -> str | None:
    """Resolve dotted-module parts to a repo-relative file among the
    indexed files (``a/b.py`` or ``a/b/__init__.py``)."""
    base = "/".join(base_parts)
    for cand in (base + ".py", base + "/__init__.py"):
        if cand in files:
            return cand
    return None


def _collect_imports(mi: ModuleInfo, files: set[str]) -> None:
    pkg_parts = mi.rel.split("/")[:-1]
    for node in ast.walk(mi.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname is None:
                    continue  # bare `import a.b` binds the root name only
                rel = _module_rel(a.name.split("."), files)
                if rel is not None:
                    mi.mod_alias[a.asname] = rel
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0:
                base = (node.module or "").split(".") if node.module else []
            else:
                base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                if node.module:
                    base = base + node.module.split(".")
            if not base:
                continue
            base_rel = _module_rel(base, files)
            for a in node.names:
                if a.name == "*":
                    continue
                bound = a.asname or a.name
                sub = _module_rel(base + [a.name], files)
                if sub is not None:
                    mi.mod_alias[bound] = sub
                elif base_rel is not None:
                    mi.sym_alias[bound] = (base_rel, a.name)


def _collect_defs_and_jit(mi: ModuleInfo) -> None:
    tree = mi.tree
    defs_by_name = mi.func_defs
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    if is_jit_call(dec):
                        mi.jit_names[node.name] = static_params(dec, node)
                        mi.trace_root_defs.add(id(node))
                    elif is_trace_wrapper_call(dec):
                        mi.trace_root_defs.add(id(node))
                elif _is_jit_expr(dec):
                    mi.jit_names[node.name] = set()
                    mi.trace_root_defs.add(id(node))
                elif last_segment(dec) in _TRACE_WRAPPERS:
                    mi.trace_root_defs.add(id(node))
        elif isinstance(node, ast.Call) and is_trace_wrapper_call(node):
            # every callable-looking argument is a registration; named
            # local defs and inline lambdas become trace roots
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Lambda):
                    mi.trace_root_defs.add(id(arg))
                elif isinstance(arg, (ast.Name, ast.Attribute)):
                    mi.registration_refs.add(id(arg))
                    seg = last_segment(arg)
                    for fn in defs_by_name.get(seg, ()):
                        mi.trace_root_defs.add(id(fn))
                elif isinstance(arg, ast.Call) and is_trace_wrapper_call(arg):
                    pass  # nested jax.jit(jax.vmap(f)) — inner visit covers f

    # `name = jax.jit(...)` / `name = jax.jit(jax.vmap(f))` assignments
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        value = node.value
        if isinstance(value, ast.Call) and is_jit_call(value):
            wrapped = value.args[0] if value.args else None
            fn = None
            if isinstance(wrapped, ast.Name):
                fns = defs_by_name.get(wrapped.id, ())
                fn = fns[0] if fns else None
            elif isinstance(wrapped, ast.Lambda):
                fn = wrapped
            mi.jit_names[target.id] = static_params(value, fn)


def _propagate_aliases(modules: dict[str, ModuleInfo]) -> None:
    """``name = other_jitted`` / ``name = mod.jitted`` aliases, to a
    fixpoint across modules (bounded — chains are short in practice)."""
    for _ in range(4):
        changed = False
        for mi in modules.values():
            for node in ast.walk(mi.tree):
                if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                    continue
                target = node.targets[0]
                if not isinstance(target, ast.Name) or target.id in mi.jit_names:
                    continue
                value = node.value
                statics = None
                if isinstance(value, ast.Name) and value.id in mi.jit_names:
                    statics = mi.jit_names[value.id]
                elif (
                    isinstance(value, ast.Attribute)
                    and isinstance(value.value, ast.Name)
                    and value.value.id in mi.mod_alias
                ):
                    other = modules.get(mi.mod_alias[value.value.id])
                    if other is not None and value.attr in other.jit_names:
                        statics = other.jit_names[value.attr]
                elif isinstance(value, ast.Name) and value.id in mi.sym_alias:
                    src_rel, sym = mi.sym_alias[value.id]
                    other = modules.get(src_rel)
                    if other is not None and sym in other.jit_names:
                        statics = other.jit_names[sym]
                if statics is not None:
                    mi.jit_names[target.id] = set(statics)
                    changed = True
        if not changed:
            return


class DeviceIndex:
    """Cross-module view: jittedness, seam membership, name resolution."""

    def __init__(self, modules: dict[str, ModuleInfo]):
        self.modules = modules

    def is_jitted(self, rel: str, name: str) -> bool:
        mi = self.modules.get(rel)
        return mi is not None and name in mi.jit_names

    def jitted_statics(self, rel: str, name: str) -> set[str]:
        mi = self.modules.get(rel)
        if mi is None:
            return set()
        return mi.jit_names.get(name, set())

    def seam_globs(self, rel: str) -> list[str]:
        return [glob for mod, glob in SEAMS if mod == rel]

    def is_seam(self, rel: str, name: str) -> bool:
        return any(fnmatch.fnmatchcase(name, g) for g in self.seam_globs(rel))

    def resolve(self, mi: ModuleInfo, node: ast.AST) -> tuple[str, str] | None:
        """(module rel, symbol) a Name/Attribute refers to, through this
        module's defs and explicit imports; None when unresolvable."""
        if isinstance(node, ast.Name):
            if node.id in mi.sym_alias:
                return mi.sym_alias[node.id]
            if node.id in mi.jit_names or node.id in mi.func_defs:
                return (mi.rel, node.id)
            return None
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            base = node.value.id
            if base in mi.mod_alias:
                return (mi.mod_alias[base], node.attr)
        return None


def build_index(
    repo_root: Path, sources=None, subdir: str = "lodestar_tpu"
) -> DeviceIndex | None:
    """Index every parsable module under ``repo_root/subdir``; None when
    the tree is absent (fixture repos without a package directory)."""
    root = Path(repo_root)
    base = root / subdir
    if not base.is_dir():
        return None
    modules: dict[str, ModuleInfo] = {}
    for path in iter_py_files([base]):
        sf = cached_source(sources, path)
        if sf is None or sf.tree is None:
            continue
        rel = path.resolve().relative_to(root.resolve()).as_posix()
        modules[rel] = ModuleInfo(rel=rel, sf=sf)
    files = set(modules)
    for mi in modules.values():
        _collect_imports(mi, files)
        _collect_defs_and_jit(mi)
    _propagate_aliases(modules)
    return DeviceIndex(modules)
