"""pow2-dispatch: arrays reaching a counted seam pass through the
shared pow-2 size-class padders.

The compiled-program cache is keyed by shape: a data-dependent leading
axis reaching a jitted program through a counted seam is one XLA
compile PER BATCH SIZE — a minutes-long compile storm at serving time,
exactly the failure the shared size-class padders (``ops/prep.pad_pow2``
/ ``pad_rows``, ``ssz/device_htr.pad_pow2_pairs``,
``models/batch_verify._pad_pow2``) exist to prevent.

The check is a backward slice at each ARRAY seam call site
(``_dispatch`` data args, ``_device_level``,
``device_batch_verify*`` — ``mesh_launch`` is exempt by contract: it
takes unpadded sets and pads inside the per-lane callables):

* PADDED — the slice (through local assignment chains) reaches a
  shared padder or another seam's output: quiet.
* RAW — the slice bottoms out at a host array constructor
  (``np.frombuffer`` / ``np.stack`` / ``np.asarray`` / ...) with no
  padder anywhere on the path AND the enclosing function never calls a
  padder at all: finding.
* UNKNOWN — parameters, attributes, helper-call results: quiet (the
  padding then happened upstream; the seam through which it arrived is
  checked at ITS call site).

The enclosing-function padder guard keeps sibling-variable flows
(pad applied to one array, concatenated via a helper into another)
from false-positives; the cost is that a function padding ONE of two
dispatched arrays stays quiet — the rule is a storm detector, not a
shape prover.
"""

from __future__ import annotations

import ast

from ..core import Finding, Rule, SourceFile
from ._device import last_segment

#: seam function name -> leading args to skip (the program callable for
#: _dispatch, the mesh for the sharded seam); mesh_launch is exempt by
#: contract (unpadded sets in, padding inside the lane callables)
SEAM_ARGS = {
    "_dispatch": 1,
    "_device_level": 0,
    "device_batch_verify": 0,
    "device_batch_verify_many": 0,
    "device_batch_verify_sharded": 1,
}

#: the shared size-class padders (plus the pad_* naming convention)
PADDERS = {"pad_pow2", "pad_rows", "pad_pow2_pairs", "_pad_pow2"}

#: host array constructors whose output shape follows their input
RAW_CONSTRUCTORS = {
    "array",
    "asarray",
    "ascontiguousarray",
    "frombuffer",
    "fromiter",
    "stack",
    "concatenate",
    "unpackbits",
    "packbits",
}

_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _np_like_aliases(tree: ast.Module) -> set[str]:
    """numpy AND jax.numpy aliases — a jnp-constructed raw shape
    recompiles just the same."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in ("numpy", "jax.numpy"):
                    out.add(a.asname or a.name.split(".")[-1])
        elif isinstance(node, ast.ImportFrom) and node.module == "jax":
            for a in node.names:
                if a.name == "numpy":
                    out.add(a.asname or "numpy")
    return out


def _is_padder_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    seg = last_segment(node.func)
    return seg is not None and (seg in PADDERS or seg.startswith("pad_"))


def _is_seam_call(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and last_segment(node.func) in SEAM_ARGS
    )


class _FunctionSlicer:
    """Backward slice through one function's local assignments."""

    def __init__(self, scope: ast.AST, np_aliases: set[str]):
        self.np_aliases = np_aliases
        self.assigns: dict[str, list[ast.AST]] = {}
        for node in ast.walk(scope):
            if node is not scope and isinstance(node, _SCOPES):
                continue
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    for name in self._target_names(t):
                        self.assigns.setdefault(name, []).append(node.value)
            elif isinstance(node, ast.AugAssign) and isinstance(
                node.target, ast.Name
            ):
                self.assigns.setdefault(node.target.id, []).append(node.value)

    @staticmethod
    def _target_names(t: ast.AST) -> list[str]:
        if isinstance(t, ast.Name):
            return [t.id]
        if isinstance(t, (ast.Tuple, ast.List)):
            return [e.id for e in t.elts if isinstance(e, ast.Name)]
        return []

    def _is_raw_call(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        # np.frombuffer(...), np.stack(...); chained .reshape() etc. is
        # handled by walking the whole expression
        return (
            isinstance(f, ast.Attribute)
            and f.attr in RAW_CONSTRUCTORS
            and isinstance(f.value, ast.Name)
            and f.value.id in self.np_aliases
        )

    def verdict(self, expr: ast.AST, _seen: set[str] | None = None) -> str:
        """'padded' | 'raw' | 'unknown' for the expression's data."""
        seen = _seen if _seen is not None else set()
        padded = raw = False

        def walk(node: ast.AST) -> None:
            nonlocal padded, raw
            if _is_padder_call(node) or _is_seam_call(node):
                padded = True
                return  # a padder/seam output is padded regardless of input
            if self._is_raw_call(node):
                raw = True
            if isinstance(node, ast.Name) and node.id in self.assigns:
                if node.id not in seen:
                    seen.add(node.id)
                    for value in self.assigns[node.id]:
                        sub = self.verdict(value, seen)
                        if sub == "padded":
                            padded = True
                        elif sub == "raw":
                            raw = True
                return
            for child in ast.iter_child_nodes(node):
                walk(child)

        walk(expr)
        if padded:
            return "padded"
        if raw:
            return "raw"
        return "unknown"


class Pow2DispatchRule(Rule):
    name = "pow2-dispatch"
    description = (
        "arrays reaching a counted dispatch seam are padded to the "
        "shared pow-2 size classes — a data-dependent shape at a jitted "
        "program is one XLA compile per batch size (a compile storm)"
    )

    def check(self, sf: SourceFile):
        tree = sf.tree
        np_aliases = _np_like_aliases(tree)
        findings: list[Finding] = []

        # enclosing function scope per seam call
        scopes: list[tuple[ast.AST | None, ast.Call]] = []

        def collect(node: ast.AST, scope: ast.AST | None) -> None:
            for child in ast.iter_child_nodes(node):
                child_scope = child if isinstance(child, _SCOPES) else scope
                if isinstance(child, ast.Call) and _is_seam_call(child):
                    scopes.append((child_scope if child_scope is not None else None, child))
                collect(child, child_scope)

        collect(tree, None)

        slicers: dict[int, _FunctionSlicer] = {}
        for scope, call in scopes:
            if scope is None:
                continue  # module-level seam calls are counted-dispatch's turf
            slicer = slicers.get(id(scope))
            if slicer is None:
                slicer = slicers[id(scope)] = _FunctionSlicer(scope, np_aliases)
            fn_has_padder = any(
                _is_padder_call(n)
                for n in ast.walk(scope)
                if not (n is not scope and isinstance(n, _SCOPES))
            )
            if fn_has_padder:
                continue
            seam = last_segment(call.func)
            skip = SEAM_ARGS[seam]
            for arg in call.args[skip:]:
                if isinstance(arg, ast.Starred):
                    continue
                if slicer.verdict(arg) == "raw":
                    findings.append(
                        Finding(
                            self.name, sf.path, call.lineno,
                            f"unpadded data-dependent shape reaching counted "
                            f"seam '{seam}': the argument slices back to a "
                            "host array constructor with no shared pow-2 "
                            "padder (pad_pow2/pad_rows/pad_pow2_pairs) on "
                            "the path — one XLA compile per batch size at "
                            "serving time; pad to a size class first",
                        )
                    )
                    break  # one finding per call site
        return findings
