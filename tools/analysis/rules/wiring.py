"""metrics-and-cli-wiring: registered metrics reach a dashboard, CLI
flags reach code, node options reach the node — both directions.

Project-scoped (inputs are fixed repo locations, independent of the
path arguments):

1. **dashboards → registry**: every metric-shaped token in a
   ``dashboards/*.json`` panel expr must be a sample name derivable
   from a registered family. Sample-name derivation generalizes the
   ``_total`` handling that bit PR 1 and PR 4: prometheus_client
   exposes a Counter named ``foo`` (or ``foo_total``) as ``foo_total``,
   a Histogram ``h`` as ``h_bucket``/``h_sum``/``h_count``, a Summary
   ``s`` as ``s``/``s_sum``/``s_count``, a Gauge verbatim.
2. **registry → dashboards**: every registered ``lodestar_*`` family
   must have at least one panel expr referencing one of its sample
   names, or an entry in ``UNPANELLED_ALLOWLIST`` with a reason — an
   unpanelled family is a blind spot during exactly the incident it
   was registered for. Allowlist entries naming no registered family
   are flagged as stale (same doctrine as unused pragmas).
3. **CLI two-way**: every ``--flag`` declared in ``lodestar_tpu/cli.py``
   is consumed (some ``args.<dest>`` read), and every ``args.<dest>``
   read has a declaring flag.
4. **node options two-way**: every ``self.X`` stored by
   ``BeaconNodeOptions.__init__`` is read as ``opts.X`` somewhere in
   ``lodestar_tpu/node/__init__.py``, and vice versa — the class of
   bug where a flag parses, stores, and then silently does nothing.

Metric families are collected statically: ``.counter("name", ...)`` /
``.gauge(...)`` / ``.histogram(...)`` calls with a literal first
argument anywhere under ``lodestar_tpu/`` (this is how every family in
the repo is declared — `RegistryMetricCreator` and the validator
monitor both go through these methods).
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass
from pathlib import Path

from ..core import Finding, Rule

#: lodestar_* families intentionally not panelled yet; every entry
#: carries the reason an operator doesn't need it on a dashboard today.
UNPANELLED_ALLOWLIST: dict[str, str] = {
    # reference-taxonomy placeholders: the device pipeline has no
    # worker dispatch/result transfer legs instrumented (the trace
    # spans bls_device_launch/bls_buffer_wait carry this decomposition)
    "lodestar_bls_thread_pool_latency_to_worker": "reference-parity placeholder; device pipeline has no worker transfer legs",
    "lodestar_bls_thread_pool_latency_from_worker": "reference-parity placeholder; device pipeline has no worker transfer legs",
    # KZG is a pre-serving workload today (no blob gossip wired); the
    # panel lands with the blob-verification dashboard
    "lodestar_kzg_device_fallback_total": "KZG pre-serving workload; panel lands with the blob-verification dashboard",
    # gossipsub router internals: debug-level detail consumed via logs /
    # ad-hoc queries, not incident dashboards
    "lodestar_gossip_mesh_peers_by_type_count": "gossipsub router debug detail",
    "lodestar_gossip_mesh_graft_total": "gossipsub router debug detail",
    "lodestar_gossip_mesh_prune_total": "gossipsub router debug detail",
    "lodestar_gossip_ihave_sent_total": "gossipsub router debug detail",
    "lodestar_gossip_iwant_received_total": "gossipsub router debug detail",
    "lodestar_gossip_iwant_served_total": "gossipsub router debug detail",
    "lodestar_gossip_mcache_size": "gossipsub router debug detail",
    "lodestar_gossip_score_by_topic": "gossipsub router debug detail",
    "lodestar_gossip_flood_publish_total": "gossipsub router debug detail",
    "lodestar_gossip_graft_backoff_violations_total": "gossipsub router debug detail",
    # peer-ops niche detail (the networking + internals dashboards carry
    # the headline peer health already)
    "lodestar_app_peer_score": "peer-scoring debug histogram; headline peer health is panelled",
    "lodestar_peers_report_peer_count_total": "peer-scoring debug detail",
    "lodestar_peer_goodbye_sent_total": "peer-ops debug detail",
    "lodestar_peer_goodbye_received_total": "peer-ops debug detail",
    "lodestar_peers_long_lived_attnets_count": "subnet-subscription debug detail",
    # discovery debug
    "lodestar_discv5_active_sessions_count": "discv5 debug detail",
    "lodestar_discv5_findnode_sent_total": "discv5 debug detail",
    "lodestar_discv5_discovered_enrs_total": "discv5 debug detail",
    "lodestar_sync_peers_by_status_count": "sync-peer classification debug detail",
    # light-client serving counters: no LC dashboard yet
    "lodestar_light_client_updates_served_total": "light-client serving; no LC dashboard yet",
    "lodestar_light_client_bootstraps_served_total": "light-client serving; no LC dashboard yet",
    # execution layer is a stub in this reproduction — panels would
    # graph constants until a real engine/builder is wired
    "lodestar_eth1_latest_block_number": "execution layer stubbed in this repro",
    "lodestar_eth1_deposit_events_total": "execution layer stubbed in this repro",
    "lodestar_eth1_requests_total": "execution layer stubbed in this repro",
    "lodestar_execution_engine_requests_total": "execution layer stubbed in this repro",
    "lodestar_execution_engine_request_seconds": "execution layer stubbed in this repro",
    "lodestar_builder_requests_total": "execution layer stubbed in this repro",
    "lodestar_builder_circuit_breaker_open": "execution layer stubbed in this repro",
}

#: PromQL functions/keywords that survive the identifier regex (the
#: old tests/metrics/test_dashboards.py list, kept verbatim)
_PROMQL_WORDS = {
    "histogram_quantile",
    "label_replace",
    "label_join",
    "group_left",
    "group_right",
    "count_values",
}

_TOKEN_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*")
#: non-metric positions stripped before tokenizing: `by (...)` /
#: `without (...)` grouping clauses and `{...}` label selectors hold
#: LABEL names (e.g. size_class), which are not sample names and must
#: not be checked against the registry
_GROUP_CLAUSE_RE = re.compile(r"\b(?:by|without)\s*\(([^)]*)\)")
_LABEL_SELECTOR_RE = re.compile(r"\{[^}]*\}")
_METRIC_METHODS = {"counter", "gauge", "histogram", "summary"}


@dataclass(frozen=True)
class Family:
    name: str
    kind: str  # counter | gauge | histogram | summary
    path: str
    line: int

    def samples(self) -> frozenset:
        """Sample names prometheus_client exposes for this family."""
        if self.kind == "counter":
            base = self.name[:-6] if self.name.endswith("_total") else self.name
            return frozenset({base + "_total"})
        if self.kind == "histogram":
            return frozenset(
                {self.name + "_bucket", self.name + "_sum", self.name + "_count"}
            )
        if self.kind == "summary":
            return frozenset({self.name, self.name + "_sum", self.name + "_count"})
        return frozenset({self.name})


def collect_metric_families(pkg_root: Path, sources=None) -> list[Family]:
    """`.counter("name", ...)`-style declarations under `pkg_root`.
    `sources` (resolved-path -> SourceFile) reuses trees analyze()
    already parsed instead of re-parsing the whole tree."""
    fams: list[Family] = []
    for path in sorted(pkg_root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        sf = sources.get(str(path.resolve())) if sources else None
        if sf is not None:
            if sf.tree is None:
                continue  # surfaced separately by the parse rule
            tree = sf.tree
        else:
            try:
                tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
            except SyntaxError:
                continue  # surfaced separately by the parse rule
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_METHODS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                fams.append(
                    Family(node.args[0].value, node.func.attr, str(path), node.lineno)
                )
    return fams


def _allowlist_line(name: str) -> int:
    """Line of `name`'s UNPANELLED_ALLOWLIST entry in this module, so a
    stale-entry finding points at the line to delete."""
    for i, line in enumerate(Path(__file__).read_text(encoding="utf-8").splitlines(), 1):
        if f'"{name}"' in line:
            return i
    return 1


def dashboard_tokens(dash_dir: Path) -> dict[str, set]:
    out: dict[str, set] = {}
    for path in sorted(dash_dir.glob("*.json")):
        tokens: set = set()
        dash = json.loads(path.read_text(encoding="utf-8"))
        for panel in dash.get("panels", []):
            for target in panel.get("targets", []):
                expr = target.get("expr", "")
                expr = _LABEL_SELECTOR_RE.sub("", expr)
                expr = _GROUP_CLAUSE_RE.sub("", expr)
                for tok in _TOKEN_RE.findall(expr):
                    if "_" in tok and tok not in _PROMQL_WORDS:
                        tokens.add(tok)
        out[str(path)] = tokens
    return out


def _cli_flags(tree: ast.Module) -> dict[str, tuple[int, str]]:
    """dest -> (line, flag spelling) for every add_argument('--x', ...)
    and add_subparsers(dest=...)."""
    flags: dict[str, tuple[int, str]] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
            continue
        if node.func.attr == "add_argument":
            opt = None
            for a in node.args:
                if isinstance(a, ast.Constant) and isinstance(a.value, str):
                    if a.value.startswith("--"):
                        opt = a.value
                        break
            if opt is None:
                continue
            dest = opt[2:].replace("-", "_")
            for kw in node.keywords:
                if kw.arg == "dest" and isinstance(kw.value, ast.Constant):
                    dest = kw.value.value
            flags.setdefault(dest, (node.lineno, opt))
        elif node.func.attr == "add_subparsers":
            for kw in node.keywords:
                if kw.arg == "dest" and isinstance(kw.value, ast.Constant):
                    flags.setdefault(kw.value.value, (node.lineno, f"subcommand dest {kw.value.value!r}"))
    return flags


def _attr_reads(tree: ast.Module, receiver: str) -> dict[str, int]:
    """attr -> first line, for every `receiver.attr` access."""
    reads: dict[str, int] = {}
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == receiver
        ):
            reads.setdefault(node.attr, node.lineno)
    return reads


def _options_stored(tree: ast.Module, class_name: str) -> dict[str, int]:
    stored: dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            for fn in node.body:
                if isinstance(fn, ast.FunctionDef) and fn.name == "__init__":
                    for sub in ast.walk(fn):
                        targets = []
                        if isinstance(sub, ast.Assign):
                            targets = sub.targets
                        elif isinstance(sub, ast.AnnAssign):
                            targets = [sub.target]
                        for t in targets:
                            if (
                                isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"
                            ):
                                stored.setdefault(t.attr, t.lineno)
    return stored


class MetricsCliWiringRule(Rule):
    name = "metrics-and-cli-wiring"
    description = (
        "metric families reach dashboards (with _total/_bucket sample "
        "derivation), CLI flags and node options are consumed, both ways"
    )
    scope = "project"

    def check_project(self, repo_root: Path, sources=None):
        findings: list[Finding] = []
        pkg = repo_root / "lodestar_tpu"
        dash_dir = repo_root / "dashboards"

        # -- metrics <-> dashboards ---------------------------------------
        if pkg.is_dir() and dash_dir.is_dir():
            fams = collect_metric_families(pkg, sources=sources)
            sample_names: set = set()
            for fam in fams:
                sample_names.update(fam.samples())
            per_dash = dashboard_tokens(dash_dir)
            all_tokens: set = set().union(*per_dash.values()) if per_dash else set()

            for dpath, tokens in per_dash.items():
                for tok in sorted(tokens - sample_names):
                    findings.append(
                        Finding(
                            self.name, dpath, 1,
                            f"panel expr references '{tok}' which no "
                            "registered metric family can expose "
                            "(counters surface as <name>_total, "
                            "histograms as _bucket/_sum/_count)",
                        )
                    )
            seen: set = set()
            for fam in fams:
                if not fam.name.startswith("lodestar_") or fam.name in seen:
                    continue
                seen.add(fam.name)
                if fam.name in UNPANELLED_ALLOWLIST:
                    continue
                if not (fam.samples() & all_tokens):
                    findings.append(
                        Finding(
                            self.name, fam.path, fam.line,
                            f"metric family '{fam.name}' ({fam.kind}) is on "
                            "no dashboard — add a panel or an "
                            "UNPANELLED_ALLOWLIST entry with a reason",
                        )
                    )
            # allowlist staleness — same doctrine as stale pragmas: an
            # entry naming no registered family is a standing license
            # for a future same-named metric to skip the panel check
            registered = {f.name for f in fams}
            for name in sorted(UNPANELLED_ALLOWLIST):
                if name not in registered:
                    findings.append(
                        Finding(
                            self.name, __file__, _allowlist_line(name),
                            f"UNPANELLED_ALLOWLIST entry '{name}' names no "
                            "registered metric family — remove the stale "
                            "entry",
                        )
                    )

        # -- CLI flags <-> consumption ------------------------------------
        cli = pkg / "cli.py"
        if cli.is_file():
            tree = ast.parse(cli.read_text(encoding="utf-8"), filename=str(cli))
            flags = _cli_flags(tree)
            reads = _attr_reads(tree, "args")
            for dest, (line, opt) in sorted(flags.items()):
                if dest not in reads:
                    findings.append(
                        Finding(
                            self.name, str(cli), line,
                            f"CLI flag {opt} (dest '{dest}') is declared but "
                            "never consumed — wire it through or drop it",
                        )
                    )
            for attr, line in sorted(reads.items()):
                if attr not in flags:
                    findings.append(
                        Finding(
                            self.name, str(cli), line,
                            f"args.{attr} is consumed but no CLI flag "
                            "declares that dest",
                        )
                    )

        # -- node options <-> consumption ---------------------------------
        node_mod = pkg / "node" / "__init__.py"
        if node_mod.is_file():
            tree = ast.parse(node_mod.read_text(encoding="utf-8"), filename=str(node_mod))
            stored = _options_stored(tree, "BeaconNodeOptions")
            reads = _attr_reads(tree, "opts")
            for attr, line in sorted(stored.items()):
                if attr not in reads:
                    findings.append(
                        Finding(
                            self.name, str(node_mod), line,
                            f"BeaconNodeOptions.{attr} is stored but the node "
                            f"never reads opts.{attr} — the option silently "
                            "does nothing",
                        )
                    )
            for attr, line in sorted(reads.items()):
                if attr not in stored:
                    findings.append(
                        Finding(
                            self.name, str(node_mod), line,
                            f"node reads opts.{attr} but BeaconNodeOptions "
                            "never stores it",
                        )
                    )
        return findings
