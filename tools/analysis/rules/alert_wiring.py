"""alert-wiring: Prometheus alert rules ↔ metric registry, both
directions — the alerting sibling of the dashboard half of
`metrics-and-cli-wiring` (same sample-name doctrine: counters surface
as ``<name>_total``, histograms as ``_bucket``/``_sum``/``_count``).

Project-scoped over two fixed locations: the statically collected
metric families under ``lodestar_tpu/`` and the committed rule files
under ``alerts/*.yml`` (JSON content — JSON is a YAML subset, written
by ``tools/gen_alerts.py``; parsed here with ``json.loads``, so the
checker stays dependency-free).

Checks:

1. **alerts → registry**: every metric-shaped token in an alert
   ``expr`` must be a sample name derivable from a registered family —
   an alert over a sample nobody exposes is a rule that can never
   fire, which reads as "we are covered" during exactly the incident
   it was written for.
2. **registry → alerts**: every ``lodestar_slo_*`` family must be
   referenced by at least one alert expr, or carry an
   ``UNALERTED_ALLOWLIST`` entry with a reason. Scoped to the SLO
   families on purpose: they exist to page someone — an SLI pair or
   miss counter no rule reads is a silent pager. (General families are
   covered by the dashboard direction of `metrics-and-cli-wiring`;
   forcing an alert per family would manufacture alert spam.)
   Allowlist entries naming no registered family are flagged as stale.
3. **rule hygiene**: every rule carries a ``severity`` label and a
   ``summary`` annotation (a page with no severity never routes; a
   firing alert with no summary is a mystery at 3am), and alert names
   are unique across all groups.
"""

from __future__ import annotations

import json
from pathlib import Path

from ..core import Finding, Rule
from .wiring import (
    _GROUP_CLAUSE_RE,
    _LABEL_SELECTOR_RE,
    _PROMQL_WORDS,
    _TOKEN_RE,
    collect_metric_families,
)

ALERTS_REL = Path("alerts")
#: family-name prefix whose members MUST be alerted (or allowlisted):
#: the SLO families exist precisely to drive the burn-rate rules
ALERTED_PREFIX = "lodestar_slo_"

#: lodestar_slo_* families intentionally carrying no alert rule yet;
#: every entry needs the reason no pager reads it today.
UNALERTED_ALLOWLIST: dict[str, str] = {}


def _allowlist_line(name: str) -> int:
    for i, line in enumerate(Path(__file__).read_text(encoding="utf-8").splitlines(), 1):
        if f'"{name}"' in line:
            return i
    return 1


def alert_expr_tokens(expr: str) -> set:
    """Metric-shaped tokens in a PromQL expr — label selectors and
    by/without grouping clauses stripped first (they hold LABEL names,
    not sample names), same tokenizer as the dashboard check."""
    expr = _LABEL_SELECTOR_RE.sub("", expr)
    expr = _GROUP_CLAUSE_RE.sub("", expr)
    return {
        tok
        for tok in _TOKEN_RE.findall(expr)
        if "_" in tok and tok not in _PROMQL_WORDS
    }


def _iter_rules(doc):
    for group in doc.get("groups", []) or []:
        for rule in group.get("rules", []) or []:
            if isinstance(rule, dict):
                yield rule


class AlertWiringRule(Rule):
    name = "alert-wiring"
    description = (
        "alert rule exprs resolve to registered metric samples, every "
        "lodestar_slo_* family is alerted (or allowlisted with a "
        "reason), and rules carry severity + summary"
    )
    scope = "project"

    def check_project(self, repo_root: Path, sources=None):
        findings: list[Finding] = []
        pkg = repo_root / "lodestar_tpu"
        alerts_dir = repo_root / ALERTS_REL
        if not pkg.is_dir() or not alerts_dir.is_dir():
            return findings  # tree without the alert tooling: nothing to wire

        fams = collect_metric_families(pkg, sources=sources)
        sample_names: set = set()
        for fam in fams:
            sample_names.update(fam.samples())

        all_tokens: set = set()
        seen_alert_names: dict[str, str] = {}
        for path in sorted(alerts_dir.glob("*.yml")):
            try:
                doc = json.loads(path.read_text(encoding="utf-8"))
            except ValueError:
                findings.append(
                    Finding(
                        self.name, str(path), 1,
                        "rule file is not the JSON-content YAML "
                        "tools/gen_alerts.py writes — regenerate it",
                    )
                )
                continue
            for rule in _iter_rules(doc):
                alert = rule.get("alert", "<unnamed>")
                # hygiene first — a broken rule should surface every
                # problem in one pass
                if not isinstance(rule.get("labels"), dict) or "severity" not in rule["labels"]:
                    findings.append(
                        Finding(
                            self.name, str(path), 1,
                            f"alert '{alert}' has no severity label — it "
                            "can never route to a pager or a ticket queue",
                        )
                    )
                if (
                    not isinstance(rule.get("annotations"), dict)
                    or "summary" not in rule["annotations"]
                ):
                    findings.append(
                        Finding(
                            self.name, str(path), 1,
                            f"alert '{alert}' has no summary annotation",
                        )
                    )
                if alert in seen_alert_names:
                    findings.append(
                        Finding(
                            self.name, str(path), 1,
                            f"alert name '{alert}' is duplicated (also in "
                            f"{seen_alert_names[alert]}) — Alertmanager "
                            "dedup would merge distinct conditions",
                        )
                    )
                else:
                    seen_alert_names[alert] = str(path)
                tokens = alert_expr_tokens(rule.get("expr", ""))
                all_tokens.update(tokens)
                for tok in sorted(tokens - sample_names):
                    findings.append(
                        Finding(
                            self.name, str(path), 1,
                            f"alert '{alert}' expr references '{tok}' which "
                            "no registered metric family can expose "
                            "(counters surface as <name>_total, histograms "
                            "as _bucket/_sum/_count) — the rule can never "
                            "fire",
                        )
                    )

        # registry → alerts, scoped to the SLO families
        seen: set = set()
        for fam in fams:
            if not fam.name.startswith(ALERTED_PREFIX) or fam.name in seen:
                continue
            seen.add(fam.name)
            if fam.name in UNALERTED_ALLOWLIST:
                continue
            if not (fam.samples() & all_tokens):
                findings.append(
                    Finding(
                        self.name, fam.path, fam.line,
                        f"SLO metric family '{fam.name}' ({fam.kind}) is "
                        "read by no alert rule — add a rule to "
                        "tools/gen_alerts.py or an UNALERTED_ALLOWLIST "
                        "entry with a reason",
                    )
                )
        registered = {f.name for f in fams}
        for name in sorted(UNALERTED_ALLOWLIST):
            if name not in registered:
                findings.append(
                    Finding(
                        self.name, __file__, _allowlist_line(name),
                        f"UNALERTED_ALLOWLIST entry '{name}' names no "
                        "registered metric family — remove the stale entry",
                    )
                )
        return findings
