"""span-discipline: spans are context-managed, never left dangling.

A ``....span(...)`` call or a ``Span(...)`` construction must be the
context expression of a ``with`` statement — a span entered any other
way never records its end and silently corrupts the trace it belongs
to (the cross-thread escape hatch is ``tracing.record(...)``, which
takes explicit start/end timestamps and is always safe).

One structural exemption: ``return ....span(...)`` inside a function
itself named ``span`` or ``root`` is a delegating wrapper (the module
facade handing out the tracer's context manager for the caller to
``with``). The tracer's internal ``Span(...)`` constructions carry
explicit pragmas instead — they are the implementation, and the
reasons belong next to the code.
"""

from __future__ import annotations

import ast

from ..core import Finding, Rule, SourceFile

_WRAPPER_NAMES = {"span", "root"}


class SpanDisciplineRule(Rule):
    name = "span-discipline"
    description = ".span(...) / Span(...) only as 'with' context managers"

    def check(self, sf: SourceFile):
        findings: list[Finding] = []
        with_ctx: set[int] = set()
        returned_by: dict[int, str] = {}
        func_stack: list[str] = []

        class _V(ast.NodeVisitor):
            def _with(self, node) -> None:
                for item in node.items:
                    if isinstance(item.context_expr, ast.Call):
                        with_ctx.add(id(item.context_expr))
                self.generic_visit(node)

            visit_With = _with
            visit_AsyncWith = _with

            def _func(self, node) -> None:
                func_stack.append(node.name)
                self.generic_visit(node)
                func_stack.pop()

            visit_FunctionDef = _func
            visit_AsyncFunctionDef = _func

            def visit_Return(self, node: ast.Return) -> None:
                if isinstance(node.value, ast.Call) and func_stack:
                    returned_by[id(node.value)] = func_stack[-1]
                self.generic_visit(node)

            def visit_Call(self, node: ast.Call) -> None:
                fn = node.func
                is_span = (
                    isinstance(fn, ast.Attribute) and fn.attr == "span"
                ) or (isinstance(fn, ast.Name) and fn.id == "Span")
                if is_span and id(node) not in with_ctx:
                    if returned_by.get(id(node)) not in _WRAPPER_NAMES:
                        what = "Span(...)" if isinstance(fn, ast.Name) else ".span(...)"
                        findings.append(
                            Finding(
                                SpanDisciplineRule.name, sf.path, node.lineno,
                                f"{what} outside a 'with' statement — the span "
                                "never ends; use 'with ... as sp:' or "
                                "tracing.record() for pre-timed spans",
                            )
                        )
                self.generic_visit(node)

        _V().visit(sf.tree)
        return findings
