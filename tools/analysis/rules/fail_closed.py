"""fail-closed-verdicts: no exception path in a verdict-returning
function may resolve True.

Verdict functions are identified by name — anything containing
``verify`` or ``verdict`` (``verify_signature_sets``, ``decode_verdict``,
``_verify_package``, ...) — or by an explicit ``-> bool`` return
annotation. Inside such a function, a ``return True`` lexically inside
an ``except`` handler is the bug class this repo's offload/pool layers
are built to exclude: an error must degrade or reject, never default
to "valid". Nested function definitions are not walked through (their
returns are not the enclosing verdict path — they get their own
check).
"""

from __future__ import annotations

import ast

from ..core import Finding, Rule, SourceFile

_NAME_MARKERS = ("verify", "verdict")


def _is_verdict_fn(node) -> bool:
    low = node.name.lower()
    if any(m in low for m in _NAME_MARKERS):
        return True
    return isinstance(node.returns, ast.Name) and node.returns.id == "bool"


def _walk_shallow(stmts):
    """Yield nodes under `stmts` without descending into nested function
    definitions or lambdas."""
    _skip = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
    stack = [s for s in stmts if not isinstance(s, _skip)]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.append(child)


class FailClosedVerdictsRule(Rule):
    name = "fail-closed-verdicts"
    description = (
        "no except path in a verify/verdict/'-> bool' function may return True"
    )

    def check(self, sf: SourceFile):
        findings: list[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _is_verdict_fn(node):
                continue
            for inner in _walk_shallow(node.body):
                if not isinstance(inner, ast.ExceptHandler):
                    continue
                for stmt in _walk_shallow(inner.body):
                    if (
                        isinstance(stmt, ast.Return)
                        and isinstance(stmt.value, ast.Constant)
                        and stmt.value.value is True
                    ):
                        findings.append(
                            Finding(
                                self.name, sf.path, stmt.lineno,
                                f"'{node.name}' returns True from an except "
                                "handler — verdict paths must fail closed "
                                "(re-raise, degrade, or return False)",
                            )
                        )
        return findings
