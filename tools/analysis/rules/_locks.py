"""Shared lexical lock tracking for the lock-aware rules.

"Holding a lock" is approximated lexically: code is considered under
lock `L` while it sits inside a `with`/`async with` statement one of
whose context expressions is the bare name `L` or an attribute access
ending in `.L` (`with self._lock:`, `with client._fs_lock:`). Lock
IDENTITY is not modeled — `with self._lock` in one object and a guarded
attribute of another object that happens to use the same lock attribute
name both pass. That is deliberate: the checker enforces the repo's
naming discipline (every shared-state lock is an attribute whose name
ends in `lock`), and the annotation names which attribute guards what.
"""

from __future__ import annotations

import ast

__all__ = ["lock_names_of_with", "looks_like_lock", "WithLockTracker"]


def _last_segment(expr: ast.expr) -> str | None:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def lock_names_of_with(node: ast.With | ast.AsyncWith) -> list[str]:
    """The trailing name of each context expression (with `as` targets
    ignored — a lock is entered, not bound)."""
    names = []
    for item in node.items:
        seg = _last_segment(item.context_expr)
        if seg is not None:
            names.append(seg)
    return names


def looks_like_lock(name: str) -> bool:
    return "lock" in name.lower()


class WithLockTracker(ast.NodeVisitor):
    """Visitor base that maintains `self.held` — the multiset of lock
    names whose `with` blocks lexically enclose the current node — and
    `self.func_stack` / `self.class_stack` for scope queries."""

    def __init__(self) -> None:
        self.held: list[str] = []
        self.func_stack: list[str] = []
        self.class_stack: list[str] = []

    # -- scope bookkeeping ----------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        self._visit_with(node)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        self._visit_with(node)

    def _visit_with(self, node) -> None:
        names = [n for n in lock_names_of_with(node) if looks_like_lock(n)]
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        self.held.extend(names)
        for stmt in node.body:
            self.visit(stmt)
        if names:
            del self.held[-len(names):]

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_func(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_func(node)

    def _visit_func(self, node) -> None:
        # decorators and default values evaluate AT DEF TIME, under
        # whatever locks enclose the def
        for dec in node.decorator_list:
            self.visit(dec)
        self._visit_defaults(node.args)
        # ...but the BODY is deferred: when it eventually runs, the
        # locks lexically enclosing the def are not (necessarily) held,
        # and an enclosing __init__ no longer confines the object — a
        # `depth_fn = lambda: self._pending` built in __init__ executes
        # later from scrape threads without the lock
        self.func_stack.append(node.name)
        saved, self.held = self.held, []
        for stmt in node.body:
            self.visit(stmt)
        self.held = saved
        self.func_stack.pop()

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_defaults(node.args)
        self.func_stack.append("<lambda>")
        saved, self.held = self.held, []
        self.visit(node.body)
        self.held = saved
        self.func_stack.pop()

    def _visit_defaults(self, args: ast.arguments) -> None:
        for d in args.defaults:
            self.visit(d)
        for d in args.kw_defaults:
            if d is not None:
                self.visit(d)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    # -- queries --------------------------------------------------------------

    def holds(self, lock: str) -> bool:
        return lock in self.held

    def in_init(self) -> bool:
        """True only when the INNERMOST function scope is __init__ —
        a nested def/lambda inside __init__ runs after construction,
        when the object is already shared."""
        return bool(self.func_stack) and self.func_stack[-1] == "__init__"

    def current_class(self) -> str | None:
        return self.class_stack[-1] if self.class_stack else None
