"""monotonic-durations: elapsed-time / deadline math never uses the
wall clock.

``time.time()`` jumps under NTP steps and leap smearing; every duration
or deadline computed from it is wrong exactly when the machine is
having a bad day. The rule flags any wall-clock read —
``time.time()`` through any module alias, or a direct
``from time import time`` name — that appears inside additive
arithmetic (``+``/``-``, including augmented assignment) or a
comparison: that is duration/deadline math and belongs to
``time.monotonic()`` / ``time.perf_counter()`` /
``time.monotonic_ns()``.

Pure timestamp uses (logging a wall time, persisting an ``at:`` field,
scaling to milliseconds) don't match and stay legal. Legitimate
wall-clock arithmetic — slot math anchored at a protocol
``genesis_time``, re-applying a persisted cool-off across restarts —
is suppressed inline with a reason, which is exactly the documentation
those sites need anyway.
"""

from __future__ import annotations

import ast

from ..core import Finding, Rule, SourceFile


def _wall_clock_names(tree: ast.Module) -> tuple[set[str], set[str]]:
    mods: set[str] = set()
    funcs: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    mods.add(a.asname or "time")
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name == "time":
                    funcs.add(a.asname or "time")
    return mods, funcs


class MonotonicDurationsRule(Rule):
    name = "monotonic-durations"
    description = (
        "no time.time() in +/- arithmetic or comparisons — use "
        "time.monotonic()/perf_counter() for durations and deadlines"
    )

    def check(self, sf: SourceFile):
        mods, funcs = _wall_clock_names(sf.tree)
        # local `import time` inside functions is caught by the walk too
        if not mods and not funcs:
            return []
        findings: list[Finding] = []
        flagged: set[int] = set()

        def is_wall_clock(node: ast.AST) -> bool:
            if not isinstance(node, ast.Call):
                return False
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr == "time"
                and isinstance(fn.value, ast.Name)
                and fn.value.id in mods
            ):
                return True
            return isinstance(fn, ast.Name) and fn.id in funcs

        def flag_calls_in(root: ast.AST) -> None:
            for sub in ast.walk(root):
                if is_wall_clock(sub) and id(sub) not in flagged:
                    flagged.add(id(sub))
                    findings.append(
                        Finding(
                            MonotonicDurationsRule.name, sf.path, sub.lineno,
                            "wall-clock time.time() used in elapsed-time/"
                            "deadline math — use time.monotonic() or "
                            "perf_counter() (NTP steps corrupt durations)",
                        )
                    )

        for node in ast.walk(sf.tree):
            if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.Add, ast.Sub)):
                flag_calls_in(node.left)
                flag_calls_in(node.right)
            elif isinstance(node, ast.AugAssign) and isinstance(
                node.op, (ast.Add, ast.Sub)
            ):
                flag_calls_in(node.value)
            elif isinstance(node, ast.Compare):
                flag_calls_in(node)
        return findings
