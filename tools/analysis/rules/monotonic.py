"""monotonic-durations: elapsed-time / deadline math never uses the
wall clock, and deterministic-harness code never reads the real clock
unconditionally.

``time.time()`` jumps under NTP steps and leap smearing; every duration
or deadline computed from it is wrong exactly when the machine is
having a bad day. The rule flags any wall-clock read —
``time.time()`` through any module alias, a direct
``from time import time`` name, or ``datetime.now()`` /
``datetime.utcnow()`` through any import spelling — that appears
inside additive arithmetic (``+``/``-``, including augmented
assignment) or a comparison: that is duration/deadline math and
belongs to ``time.monotonic()`` / ``time.perf_counter()`` /
``time.monotonic_ns()``.

Pure timestamp uses (logging a wall time, persisting an ``at:`` field,
scaling to milliseconds) don't match and stay legal. Legitimate
wall-clock arithmetic — slot math anchored at a protocol
``genesis_time``, re-applying a persisted cool-off across restarts —
is suppressed inline with a reason, which is exactly the documentation
those sites need anyway.

SimClock-awareness (``testing/`` code only): the deterministic fleet
harness injects a ``SimClock`` so chaos runs replay byte-identically.
A bare ``time.time()`` / ``time.monotonic*()`` / ``time.perf_counter*()``
CALL in harness code silently reintroduces real time into a simulated
run. The legal idiom guards the real clock behind a clock-is-None
conditional (``self.clock.time() if self.clock is not None else
time.time()``) — any real-clock call with an enclosing ``if``/ternary
whose test mentions a clock is exempt, as is passing the function VALUE
(``time_fn=time.monotonic_ns``: a reference, not a read). ``clock.py``
itself (the SimClock implementation) is exempt wholesale.
"""

from __future__ import annotations

import ast
from pathlib import Path

from ..core import Finding, Rule, SourceFile

#: real-clock readers that bypass an injected SimClock in harness code
_REAL_CLOCK_FNS = {
    "time",
    "monotonic",
    "monotonic_ns",
    "perf_counter",
    "perf_counter_ns",
}


def _wall_clock_names(tree: ast.Module) -> tuple[set[str], set[str]]:
    mods: set[str] = set()
    funcs: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    mods.add(a.asname or "time")
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name == "time":
                    funcs.add(a.asname or "time")
    return mods, funcs


def _datetime_names(tree: ast.Module) -> tuple[set[str], set[str]]:
    """(module aliases of ``datetime``, class aliases of
    ``datetime.datetime``) — both spellings of now()/utcnow()."""
    mods: set[str] = set()
    classes: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "datetime":
                    mods.add(a.asname or "datetime")
        elif isinstance(node, ast.ImportFrom) and node.module == "datetime":
            for a in node.names:
                if a.name == "datetime":
                    classes.add(a.asname or "datetime")
    return mods, classes


def _real_clock_funcs(tree: ast.Module) -> set[str]:
    """Local aliases of ``from time import monotonic/perf_counter/...``
    — real-clock reads for the SimClock check, but NOT wall-clock reads
    for the duration check (monotonic arithmetic is the fix, not the
    bug)."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name in _REAL_CLOCK_FNS:
                    out.add(a.asname or a.name)
    return out


def _mentions_clock(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and "clock" in sub.id.lower():
            return True
        if isinstance(sub, ast.Attribute) and "clock" in sub.attr.lower():
            return True
    return False


class MonotonicDurationsRule(Rule):
    name = "monotonic-durations"
    description = (
        "no time.time()/datetime.now()/utcnow() in +/- arithmetic or "
        "comparisons (use time.monotonic()/perf_counter()), and no "
        "unconditional real-clock reads in testing/ harness code (the "
        "injected SimClock must stay authoritative)"
    )

    def check(self, sf: SourceFile):
        mods, funcs = _wall_clock_names(sf.tree)
        dt_mods, dt_classes = _datetime_names(sf.tree)
        real_funcs = funcs | _real_clock_funcs(sf.tree)
        # local `import time` inside functions is caught by the walk too
        if not mods and not real_funcs and not dt_mods and not dt_classes:
            return []
        findings: list[Finding] = []
        flagged: set[int] = set()

        def is_wall_clock(node: ast.AST) -> bool:
            if not isinstance(node, ast.Call):
                return False
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr == "time"
                and isinstance(fn.value, ast.Name)
                and fn.value.id in mods
            ):
                return True
            if isinstance(fn, ast.Attribute) and fn.attr in ("now", "utcnow"):
                recv = fn.value
                # datetime.now() via the class alias
                if isinstance(recv, ast.Name) and recv.id in dt_classes:
                    return True
                # datetime.datetime.now() via the module alias
                if (
                    isinstance(recv, ast.Attribute)
                    and recv.attr == "datetime"
                    and isinstance(recv.value, ast.Name)
                    and recv.value.id in dt_mods
                ):
                    return True
            return isinstance(fn, ast.Name) and fn.id in funcs

        def flag_calls_in(root: ast.AST) -> None:
            for sub in ast.walk(root):
                if is_wall_clock(sub) and id(sub) not in flagged:
                    flagged.add(id(sub))
                    findings.append(
                        Finding(
                            MonotonicDurationsRule.name, sf.path, sub.lineno,
                            "wall-clock read (time.time()/datetime.now()/"
                            "utcnow()) used in elapsed-time/deadline math — "
                            "use time.monotonic() or perf_counter() (NTP "
                            "steps corrupt durations)",
                        )
                    )

        for node in ast.walk(sf.tree):
            if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.Add, ast.Sub)):
                flag_calls_in(node.left)
                flag_calls_in(node.right)
            elif isinstance(node, ast.AugAssign) and isinstance(
                node.op, (ast.Add, ast.Sub)
            ):
                flag_calls_in(node.value)
            elif isinstance(node, ast.Compare):
                flag_calls_in(node)

        findings.extend(self._simclock_findings(sf, mods, real_funcs, flagged))
        return findings

    def _simclock_findings(
        self,
        sf: SourceFile,
        mods: set[str],
        funcs: set[str],
        flagged: set[int],
    ) -> list[Finding]:
        """Unconditional real-clock CALLS in ``testing/`` harness code."""
        parts = Path(sf.path).parts
        if "testing" not in parts or Path(sf.path).name == "clock.py":
            return []

        def is_real_clock_call(node: ast.AST) -> bool:
            if not isinstance(node, ast.Call):
                return False
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr in _REAL_CLOCK_FNS
                and isinstance(fn.value, ast.Name)
                and fn.value.id in mods
            ):
                return True
            return isinstance(fn, ast.Name) and fn.id in funcs

        parent: dict[int, ast.AST] = {}
        for node in ast.walk(sf.tree):
            for child in ast.iter_child_nodes(node):
                parent[id(child)] = node

        findings: list[Finding] = []
        for node in ast.walk(sf.tree):
            if not is_real_clock_call(node) or id(node) in flagged:
                continue
            # climb: exempt when any enclosing if/ternary tests a clock
            # (the clock-is-None fallback idiom)
            guarded = False
            cur: ast.AST | None = node
            while cur is not None:
                p = parent.get(id(cur))
                if isinstance(p, (ast.If, ast.IfExp)) and _mentions_clock(p.test):
                    guarded = True
                    break
                cur = p
            if guarded:
                continue
            flagged.add(id(node))
            findings.append(
                Finding(
                    self.name, sf.path, node.lineno,
                    "testing/ harness code reads the real clock "
                    "unconditionally — consult the injected SimClock and "
                    "fall back to the real clock only behind a "
                    "clock-is-None conditional (deterministic replays "
                    "must not see real time)",
                )
            )
        return findings
