"""bench-wiring: bench line names ↔ trajectory regression thresholds,
both directions — the cross-file sibling of the metrics/REST/fault
wiring rules (same doctrine: a bench line the gate never checks, or a
threshold gating a line nobody emits, silently does nothing exactly
when the chip run depends on it).

Project-scoped over four fixed locations:

* ``tools/baseline_configs_bench.py`` and
  ``tools/chaos_experiment.py`` — every ``_line("name", ...)``
  reporting call. Literal first args are exact line names; f-string
  first args (``f"mesh_sigs_per_sec_{n}dev"``) become match patterns
  with each interpolation treated as a wildcard; anything else (a bare
  variable) is flagged — a dynamically-built name cannot be statically
  gated, so the reporting seam must keep names derivable.
* ``bench.py`` (repo root) — dict literals carrying a constant
  ``"metric"`` key (the config-1 headline shape the r1–r5 trajectory
  files record).
* ``tools/bench_trajectory.py`` — the ``THRESHOLDS`` dict literal (the
  per-line regression gate) and ``LOWER_IS_BETTER`` (direction set).

Checks:

1. **thresholds → bench**: every ``THRESHOLDS`` key must be emitted by
   some reporting call (exact literal or f-string pattern match) — a
   stale threshold is a standing license for a renamed line to escape
   the gate.
2. **bench → thresholds**: every literal line name must have a
   ``THRESHOLDS`` entry, and every f-string pattern must match at
   least one — an ungated line regresses silently on the next round.
3. **direction hygiene**: every ``LOWER_IS_BETTER`` member must be a
   ``THRESHOLDS`` key — a direction flag for a nonexistent metric is
   dead configuration.
4. **launch-budget direction**: a ``THRESHOLDS`` key naming a
   launch-budget line (``*_launches_per_batch*`` / ``*_launches_per_set*``,
   variant tails like ``_split``/``_unfused`` included) must be a
   ``LOWER_IS_BETTER`` member — more launches is the regression, and a
   budget line silently gating in the higher-is-better direction would
   PASS a schedule that grew a launch and FAIL the next round that
   removed one.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from ..core import Finding, Rule

BENCH_REL = Path("tools") / "baseline_configs_bench.py"
CHAOS_REL = Path("tools") / "chaos_experiment.py"
HEADLINE_REL = Path("bench.py")
TRAJECTORY_REL = Path("tools") / "bench_trajectory.py"
REPORT_FN = "_line"
THRESHOLDS_NAME = "THRESHOLDS"
DIRECTION_NAME = "LOWER_IS_BETTER"
#: metric-name markers that denote a launch-budget line (a dispatch
#: count, where MORE is the regression) — these must gate
#: lower-is-better. Matched ANYWHERE in the key, not as an exact
#: suffix: variant tails are an active naming pattern
#: ("prep_launches_per_set_unfused", "e2e_launches_per_batch_split")
#: and a suffixed budget line evading the check would gate a grown
#: launch as an improvement.
LAUNCH_BUDGET_MARKERS = ("_launches_per_batch", "_launches_per_set")


def _parse(path: Path):
    try:
        return ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
    except SyntaxError:
        return None  # surfaced separately by the parse rule


def _reported_names(tree: ast.Module):
    """(exact names, (pattern, source_text) pairs, non-static finding
    sites) from `_line(first_arg, ...)` calls."""
    exact: list[tuple[str, int]] = []
    patterns: list[tuple[re.Pattern, str, int]] = []
    dynamic: list[int] = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == REPORT_FN
            and node.args
        ):
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            exact.append((first.value, node.lineno))
        elif isinstance(first, ast.JoinedStr):
            parts = []
            text = []
            for piece in first.values:
                if isinstance(piece, ast.Constant):
                    parts.append(re.escape(str(piece.value)))
                    text.append(str(piece.value))
                else:
                    # .*? not .+?: an interpolation may be empty (the
                    # `_line(f"name{suffix}")` pattern with suffix "")
                    parts.append(".*?")
                    text.append("{…}")
            patterns.append((re.compile("^" + "".join(parts) + "$"), "".join(text), node.lineno))
        else:
            dynamic.append(node.lineno)
    return exact, patterns, dynamic


def _headline_names(tree: ast.Module) -> list[tuple[str, int]]:
    """Constant "metric" values in dict literals (bench.py's one-line
    JSON shape)."""
    out: list[tuple[str, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for key, value in zip(node.keys, node.values):
            if (
                isinstance(key, ast.Constant)
                and key.value == "metric"
                and isinstance(value, ast.Constant)
                and isinstance(value.value, str)
            ):
                out.append((value.value, value.lineno))
    return out


def _dict_literal_keys(tree: ast.Module, name: str) -> dict[str, int]:
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        if not any(isinstance(t, ast.Name) and t.id == name for t in targets):
            continue
        value = node.value
        if isinstance(value, ast.Dict):
            return {
                k.value: k.lineno
                for k in value.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)
            }
    return {}


def _set_literal_members(tree: ast.Module, name: str) -> dict[str, int]:
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        if not any(isinstance(t, ast.Name) and t.id == name for t in targets):
            continue
        value = node.value
        if isinstance(value, ast.Set):
            return {
                e.value: e.lineno
                for e in value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            }
    return {}


class BenchWiringRule(Rule):
    name = "bench-wiring"
    description = (
        "bench line names and trajectory regression thresholds agree "
        "both ways (literal/f-string derivable reporting, gated lines, "
        "direction-set hygiene)"
    )
    scope = "project"

    def check_project(self, repo_root: Path, sources=None):
        findings: list[Finding] = []
        bench_path = repo_root / BENCH_REL
        traj_path = repo_root / TRAJECTORY_REL
        if not bench_path.is_file() or not traj_path.is_file():
            return findings  # tree without the bench tooling: nothing to wire
        bench_tree = _parse(bench_path)
        traj_tree = _parse(traj_path)
        if bench_tree is None or traj_tree is None:
            return findings

        # carry the SOURCE file per reported name so an ungated line is
        # reported against the file that emits it, not misattributed to
        # baseline_configs_bench.py at an unrelated line
        exact: list[tuple[str, str, int]] = []
        patterns: list[tuple[re.Pattern, str, str, int]] = []
        reporters = [(bench_path, bench_tree)]
        chaos_path = repo_root / CHAOS_REL
        if chaos_path.is_file():
            chaos_tree = _parse(chaos_path)
            if chaos_tree is not None:
                reporters.append((chaos_path, chaos_tree))
        for src_path, tree in reporters:
            src_exact, src_patterns, dynamic = _reported_names(tree)
            exact.extend((name, str(src_path), line) for name, line in src_exact)
            patterns.extend(
                (pat, text, str(src_path), line)
                for pat, text, line in src_patterns
            )
            for line in dynamic:
                findings.append(
                    Finding(
                        self.name, str(src_path), line,
                        f"{REPORT_FN}() first argument is not a literal or "
                        "f-string — the bench line name cannot be statically "
                        "gated by the trajectory thresholds",
                    )
                )
        headline_path = repo_root / HEADLINE_REL
        if headline_path.is_file():
            headline_tree = _parse(headline_path)
            if headline_tree is not None:
                for name, line in _headline_names(headline_tree):
                    exact.append((name, str(headline_path), line))

        thresholds = _dict_literal_keys(traj_tree, THRESHOLDS_NAME)
        direction = _set_literal_members(traj_tree, DIRECTION_NAME)
        if not thresholds:
            findings.append(
                Finding(
                    self.name, str(traj_path), 1,
                    f"no literal {THRESHOLDS_NAME} dict found — the "
                    "regression gate has no statically checkable lines",
                )
            )
            return findings

        exact_names = {n for n, _, _ in exact}
        # thresholds -> bench: every gated name is actually reported
        for key, line in sorted(thresholds.items()):
            if key in exact_names:
                continue
            if any(p.match(key) for p, _, _, _ in patterns):
                continue
            findings.append(
                Finding(
                    self.name, str(traj_path), line,
                    f"{THRESHOLDS_NAME} entry '{key}' names no bench line "
                    "reported by baseline_configs_bench.py, "
                    "chaos_experiment.py, or bench.py — remove the stale "
                    "threshold or fix the line name",
                )
            )
        # bench -> thresholds: every reported line is gated
        seen: set = set()
        for name, src_path, line in exact:
            if name in seen:
                continue
            seen.add(name)
            if name not in thresholds:
                findings.append(
                    Finding(
                        self.name, src_path, line,
                        f"bench line '{name}' has no {THRESHOLDS_NAME} entry "
                        "in bench_trajectory.py — the line would regress "
                        "ungated",
                    )
                )
        for pattern, text, src_path, line in patterns:
            if not any(pattern.match(key) for key in thresholds):
                findings.append(
                    Finding(
                        self.name, src_path, line,
                        f"bench line pattern '{text}' matches no "
                        f"{THRESHOLDS_NAME} entry — the lines it emits would "
                        "regress ungated",
                    )
                )
        # direction hygiene
        for member, line in sorted(direction.items()):
            if member not in thresholds:
                findings.append(
                    Finding(
                        self.name, str(traj_path), line,
                        f"{DIRECTION_NAME} member '{member}' is not a "
                        f"{THRESHOLDS_NAME} key — dead direction flag",
                    )
                )
        # launch-budget direction: a dispatch-count line gating
        # higher-is-better would pass a schedule that GREW a launch
        for key, line in sorted(thresholds.items()):
            if any(m in key for m in LAUNCH_BUDGET_MARKERS) and key not in direction:
                findings.append(
                    Finding(
                        self.name, str(traj_path), line,
                        f"{THRESHOLDS_NAME} entry '{key}' is a "
                        f"launch-budget line but not a {DIRECTION_NAME} "
                        "member — it would gate in the wrong direction "
                        "(more launches must be the regression)",
                    )
                )
        return findings
