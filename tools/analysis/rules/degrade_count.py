"""degrade-and-count: every ``except`` wrapping a device dispatch both
ticks a fallback counter and routes to a named host path.

The degradation chain (single-launch → split schedule → host prep;
device HTR → CPU hasher; device KZG → CPU oracle) is the reason a
device fault is an alert, not an outage — but ONLY if every degradation
is observable and lands somewhere deliberate. An ``except`` around a
device dispatch that swallows the error silently serves wrong-shaped
work with no counter movement: the fleet is degraded and every
dashboard says it is healthy.

For each ``try`` whose body contains a device dispatch — a call
resolving to a counted seam or a jit-wrapped callable, or a seam/jitted
callable passed as an argument (the stored-then-dispatched shape, e.g.
``self._flush_with(_device_level, ...)``) — every handler must either:

* **re-raise** (propagation/conversion is not degradation), or
* **count AND route**: tick a ``*fallback*`` counter (a call whose
  dotted name contains "fallback" — ``note_fallback(e)``,
  ``m.fallbacks.labels(leg).inc()`` — the metrics-wiring rule keeps
  those families registered and panelled) and hand control to a named
  host path: a ``return <call>(...)``, a statement call naming a
  host-ish target (cpu/host/split/oracle/unfused/fallback), or plain
  fall-through into the code after the ``try`` (the
  ``build_device_inputs`` shape, where the host path is the next
  statement).

A handler that counts but dead-ends in ``return None``/``return False``
is still a finding: the caller can't distinguish "device degraded" from
a verdict, which is how silent wrong-shape serving starts. ``try``
blocks inside trace-time bodies are exempt (they run at trace, not at
dispatch).
"""

from __future__ import annotations

import ast
from pathlib import Path

from ..core import Finding, Rule
from ._device import DeviceIndex, ModuleInfo, build_index, dotted, last_segment

_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

#: tokens that mark a statement call as a named host-path handoff
HOST_TOKENS = ("cpu", "host", "split", "oracle", "fallback", "unfused")


def _resolves_to_dispatch(idx: DeviceIndex, mi: ModuleInfo, node: ast.AST) -> bool:
    target = idx.resolve(mi, node)
    if target is None:
        return False
    rel, name = target
    return idx.is_jitted(rel, name) or idx.is_seam(rel, name)


def _try_dispatches(idx: DeviceIndex, mi: ModuleInfo, body: list[ast.stmt]) -> bool:
    for stmt in body:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            if _resolves_to_dispatch(idx, mi, node.func):
                return True
            # a seam/jitted callable handed onward as an argument —
            # the stored-then-dispatched shape
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, (ast.Name, ast.Attribute)) and _resolves_to_dispatch(
                    idx, mi, arg
                ):
                    return True
    return False


def _handler_raises(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for n in ast.walk(handler))


def _handler_counts(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func) or last_segment(node.func) or ""
        if "fallback" in name.lower():
            return True
        # m.fallbacks.labels("leg").inc(): the receiver chain is a Call,
        # so dotted() can't see it — stringify the receiver of .inc()
        if isinstance(node.func, ast.Attribute) and node.func.attr == "inc":
            recv = node.func.value
            while isinstance(recv, ast.Call):
                recv = recv.func
            if "fallback" in (dotted(recv) or "").lower():
                return True
    return False


def _handler_routes(handler: ast.ExceptHandler) -> bool:
    returns = [n for n in ast.walk(handler) if isinstance(n, ast.Return)]
    if any(isinstance(r.value, ast.Call) for r in returns):
        return True
    for stmt in ast.walk(handler):
        if not isinstance(stmt, (ast.Expr, ast.Assign)):
            continue
        value = stmt.value
        if not isinstance(value, ast.Call):
            continue
        names = [dotted(value.func) or ""]
        names += [
            dotted(a) or ""
            for a in list(value.args) + [k.value for k in value.keywords]
        ]
        if any(tok in n.lower() for n in names for tok in HOST_TOKENS):
            return True
    # no return at all: the handler falls through to the statements
    # after the try — the host path is the next code to run
    return not returns


class DegradeAndCountRule(Rule):
    name = "degrade-and-count"
    description = (
        "every except wrapping a device dispatch ticks a *fallback* "
        "counter AND routes to a named host path (or re-raises) — "
        "silent or uncounted degradation serves wrong-shaped work "
        "while every dashboard reads healthy"
    )
    scope = "project"

    def check_project(self, repo_root: Path, sources=None):
        idx = build_index(repo_root, sources)
        if idx is None:
            return []
        findings: list[Finding] = []
        for rel in sorted(idx.modules):
            mi = idx.modules[rel]
            # try statements with their innermost enclosing function
            stack: list[ast.AST] = []
            trys: list[tuple[ast.Try, ast.AST | None]] = []

            def collect(node: ast.AST) -> None:
                is_scope = isinstance(node, _SCOPES)
                if is_scope:
                    stack.append(node)
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, ast.Try):
                        trys.append((child, stack[-1] if stack else None))
                    collect(child)
                if is_scope:
                    stack.pop()

            collect(mi.tree)

            for try_node, scope in trys:
                if scope is not None and id(scope) in mi.trace_root_defs:
                    continue  # trace-time try: runs at trace, not dispatch
                if not _try_dispatches(idx, mi, try_node.body):
                    continue
                for handler in try_node.handlers:
                    if _handler_raises(handler):
                        continue
                    counts = _handler_counts(handler)
                    routes = _handler_routes(handler)
                    if counts and routes:
                        continue
                    missing = []
                    if not counts:
                        missing.append(
                            "ticks no *fallback* counter (the degradation "
                            "is invisible to alerts)"
                        )
                    if not routes:
                        missing.append(
                            "names no host path (dead-end return instead "
                            "of a fallback callable or fall-through)"
                        )
                    findings.append(
                        Finding(
                            self.name,
                            str(repo_root / rel),
                            handler.lineno,
                            "except wraps a device dispatch but "
                            + " and ".join(missing)
                            + " — degrade-and-count: count the fallback "
                            "and route to a named host path, or re-raise",
                        )
                    )
        return findings
