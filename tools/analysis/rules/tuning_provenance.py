"""tuning-provenance: every constant TUNING.md claims provenance for
actually exists where the table says it lives.

TUNING.md is the provenance ledger for hand-tuned constants — each row
names a constant (backticked, column 1) and its defining module
(backticked path, column 3), written by `tools/chaos_experiment.py
--write-tuning`. The ledger is only worth trusting if it cannot go
stale silently: a constant renamed or moved after its experiment row
was recorded would leave the table pointing at nothing, and the next
reader re-tuning "the documented value" would be reading fiction.

Project-scoped checks over ``TUNING.md`` rows:

1. the referenced file exists in the tree;
2. the file contains a module-level assignment (plain or annotated)
   binding exactly that constant name.

A repo without a TUNING.md has nothing to check — the rule only gates
trees that carry the ledger.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from ..core import Finding, Rule

TUNING_REL = Path("TUNING.md")

#: | `CONSTANT` | value | `path/to/file.py` | ...
_ROW_RE = re.compile(
    r"^\|\s*`(?P<constant>[A-Za-z_][A-Za-z0-9_]*)`\s*\|"
    r"[^|]*\|\s*`(?P<path>[^`|]+)`\s*\|"
)


def _table_rows(text: str):
    """(constant, path, line_number) per provenance row."""
    for i, line in enumerate(text.splitlines(), start=1):
        m = _ROW_RE.match(line.strip())
        if m:
            yield m.group("constant"), m.group("path").strip(), i


def _module_level_names(tree: ast.Module) -> set:
    names: set = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            names.add(node.target.id)
    return names


class TuningProvenanceRule(Rule):
    name = "tuning-provenance"
    description = (
        "every constant in the TUNING.md provenance table exists as a "
        "module-level assignment in the file the table names"
    )
    scope = "project"

    def check_project(self, repo_root: Path, sources=None):
        findings: list[Finding] = []
        tuning_path = repo_root / TUNING_REL
        if not tuning_path.is_file():
            return findings  # no ledger, nothing to go stale
        text = tuning_path.read_text(encoding="utf-8")
        parsed: dict[Path, set | None] = {}
        for constant, rel, line in _table_rows(text):
            target = repo_root / rel
            if not target.is_file():
                findings.append(
                    Finding(
                        self.name, str(tuning_path), line,
                        f"provenance row for '{constant}' names missing "
                        f"file '{rel}'",
                    )
                )
                continue
            if target not in parsed:
                try:
                    parsed[target] = _module_level_names(
                        ast.parse(target.read_text(encoding="utf-8"))
                    )
                except SyntaxError:
                    parsed[target] = None  # surfaced by the parse rule
            names = parsed[target]
            if names is not None and constant not in names:
                findings.append(
                    Finding(
                        self.name, str(tuning_path), line,
                        f"provenance row names constant '{constant}' but "
                        f"'{rel}' has no module-level assignment binding it "
                        "— the ledger went stale (renamed/moved constant?)",
                    )
                )
        return findings
