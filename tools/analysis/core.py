"""Core of the project static-analysis pass: findings, suppression
pragmas, parsed source files, and the runner.

The checkers encode invariants this repo has already shipped bugs
against (lock discipline, fail-closed verdict paths, context-managed
spans, monotonic duration math, metrics/CLI wiring). They are AST-based
(stdlib `ast` only) and run as a tier-1 gate (`tests/analysis/`) plus a
CLI: `python -m tools.analysis [--rule NAME] [paths...]`.

Suppression pragma (same line as the finding, or on a `def`/`class`
line to cover the whole scope)::

    # lint: allow(rule-name) — why this is intentionally exempt

A reason is REQUIRED: a pragma without one is itself reported (rule
`pragma`), as is a pragma naming an unknown rule or — on full-rule runs
— a pragma that no longer suppresses anything (stale suppressions rot
into licenses to regress).
"""

from __future__ import annotations

import ast
import io
import re
import time
import tokenize
from dataclasses import dataclass, field
from pathlib import Path

__all__ = [
    "Finding",
    "Pragma",
    "SourceFile",
    "Rule",
    "analyze",
    "cached_source",
    "iter_py_files",
]

#: `# lint: allow(rule[, rule...])` with a mandatory free-text reason
#: after an em/en dash, double hyphen, or single hyphen separator
_PRAGMA_RE = re.compile(
    r"#\s*lint:\s*allow\(([^)]*)\)\s*(?:(?:—|–|--|-|:)\s*(\S.*))?\s*$"
)


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"


@dataclass
class Pragma:
    line: int
    rules: frozenset
    reason: str
    used: bool = False


class SourceFile:
    """One parsed module: text, AST, comments, and suppression pragmas."""

    def __init__(self, path: str, text: str):
        self.path = path
        self.text = text
        self.lines = text.splitlines()
        self.tree: ast.Module | None = None
        self.parse_error: str | None = None
        try:
            self.tree = ast.parse(text, filename=path)
        except SyntaxError as e:
            self.parse_error = f"{e.msg} (line {e.lineno})"
        #: line -> comment text (via tokenize, so '#' inside strings is
        #: not mistaken for a comment)
        self.comments: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError):
            pass
        self.pragmas: dict[int, Pragma] = {}
        self.malformed_pragmas: list[Finding] = []
        for line, comment in self.comments.items():
            if "lint:" not in comment:
                continue
            m = _PRAGMA_RE.search(comment)
            if m is None:
                self.malformed_pragmas.append(
                    Finding("pragma", path, line, f"unparseable lint pragma: {comment.strip()!r}")
                )
                continue
            rules = frozenset(r.strip() for r in m.group(1).split(",") if r.strip())
            reason = (m.group(2) or "").strip()
            if not rules:
                self.malformed_pragmas.append(
                    Finding("pragma", path, line, "lint pragma names no rule")
                )
                continue
            if not reason:
                self.malformed_pragmas.append(
                    Finding(
                        "pragma", path, line,
                        "suppression pragma carries no reason "
                        "(format: # lint: allow(rule) — why)",
                    )
                )
                continue
            self.pragmas[line] = Pragma(line, rules, reason)
        #: (first_line, last_line, pragma) for pragmas sitting on a
        #: def/class line: they cover the whole scope
        self.scoped: list[tuple[int, int, Pragma]] = []
        if self.tree is not None:
            for node in ast.walk(self.tree):
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                ):
                    p = self.pragmas.get(node.lineno)
                    if p is not None:
                        self.scoped.append((node.lineno, node.end_lineno or node.lineno, p))

    @classmethod
    def load(cls, path: str | Path) -> "SourceFile":
        p = Path(path)
        return cls(str(p), p.read_text(encoding="utf-8"))

    def _comment_only(self, line: int) -> bool:
        text = self.lines[line - 1] if 0 < line <= len(self.lines) else ""
        return text.lstrip().startswith("#")

    def suppression(self, rule: str, line: int) -> Pragma | None:
        """The pragma suppressing `rule` at `line`: same-line trailing
        comment, a comment-only pragma line immediately above, or an
        enclosing def/class-scope pragma."""
        p = self.pragmas.get(line)
        if p is not None and rule in p.rules:
            return p
        p = self.pragmas.get(line - 1)
        if p is not None and rule in p.rules and self._comment_only(line - 1):
            return p
        for first, last, sp in self.scoped:
            if first <= line <= last and rule in sp.rules:
                return sp
        return None


class Rule:
    """Base: per-file rules implement `check(sf)`; project-scoped rules
    set `scope = "project"` and implement `check_project(repo_root)`."""

    name: str = ""
    description: str = ""
    scope: str = "file"

    def check(self, sf: SourceFile):  # pragma: no cover - interface
        return ()

    def check_project(self, repo_root: Path, sources=None):  # pragma: no cover - interface
        """`sources` (resolved-path -> SourceFile) lets a project rule
        reuse the trees analyze() already parsed."""
        return ()


def cached_source(sources, path) -> SourceFile | None:
    """The one parsed-AST cache shared across a run: project rules load
    files through here so the same module is parsed once no matter how
    many rules scan it. `sources` is the resolved-path-keyed dict
    `analyze()` passes to `check_project` (None falls back to a plain
    load). Unreadable/missing files return None."""
    p = Path(path)
    key = str(p.resolve())
    sf = sources.get(key) if sources is not None else None
    if sf is None:
        if p.suffix != ".py" or not p.is_file():
            return None
        try:
            sf = SourceFile.load(p)
        except OSError:
            return None
        if sources is not None:
            sources[key] = sf
    return sf


def iter_py_files(paths) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            out.extend(
                f for f in p.rglob("*.py") if "__pycache__" not in f.parts
            )
        elif p.suffix == ".py":
            out.append(p)
    return sorted(set(out))


def analyze(
    paths,
    *,
    rules=None,
    repo_root: Path | None = None,
    pragma_hygiene: bool | None = None,
    stats: dict | None = None,
) -> list[Finding]:
    """Run `rules` (default: all registered) over `paths`. Project-scoped
    rules run once against `repo_root` (default: this repo). Returns the
    unsuppressed findings, sorted; on full-rule runs, stale/malformed
    pragmas are reported under the `pragma` rule (`pragma_hygiene`
    overrides that default — tests exercise hygiene against a single
    rule without paying for the project-scoped ones). Pass a dict as
    `stats` to receive per-rule accounting:
    ``{rule_name: {"findings": n, "seconds": s}}`` (findings counted
    AFTER suppression — the number the operator actually sees)."""
    from .rules import ALL_RULES

    selected = list(ALL_RULES) if rules is None else list(rules)
    full_run = (rules is None) if pragma_hygiene is None else pragma_hygiene
    if repo_root is None:
        repo_root = Path(__file__).resolve().parents[2]

    # keyed by RESOLVED path: per-file rules emit findings spelled the
    # way the caller passed the path (possibly relative) while project
    # rules emit absolute paths — a spelling-keyed cache would load the
    # same file twice and mark a pragma used on one copy while the
    # other copy's identical pragma reports stale
    sources: dict[str, SourceFile] = {}
    analyzed: set[str] = set()

    def source_for(path: str) -> SourceFile | None:
        key = str(Path(path).resolve())
        sf = sources.get(key)
        if sf is None and Path(path).suffix == ".py" and Path(path).exists():
            sf = sources[key] = SourceFile.load(path)
        return sf

    findings: list[Finding] = []
    for f in iter_py_files(paths):
        sf = SourceFile.load(f)
        key = str(f.resolve())
        sources[key] = sf
        analyzed.add(key)
        if sf.parse_error is not None:
            findings.append(Finding("parse", sf.path, 1, f"syntax error: {sf.parse_error}"))

    raw: list[Finding] = []
    rule_seconds: dict[str, float] = {}
    for rule in selected:
        t0 = time.monotonic()
        if rule.scope == "project":
            raw.extend(rule.check_project(repo_root, sources=sources))
        else:
            for path in sorted(analyzed):
                sf = sources[path]
                if sf.tree is not None:
                    raw.extend(rule.check(sf))
        rule_seconds[rule.name] = time.monotonic() - t0

    kept_by_rule: dict[str, int] = {}
    for fnd in raw:
        sf = source_for(fnd.path)
        if sf is not None:
            p = sf.suppression(fnd.rule, fnd.line)
            if p is not None:
                p.used = True
                continue
        kept_by_rule[fnd.rule] = kept_by_rule.get(fnd.rule, 0) + 1
        findings.append(fnd)

    if stats is not None:
        for rule in selected:
            stats[rule.name] = {
                "findings": kept_by_rule.get(rule.name, 0),
                "seconds": rule_seconds.get(rule.name, 0.0),
            }

    if full_run:
        # pragma hygiene only for files the caller actually analyzed —
        # files loaded lazily for suppression lookups (e.g. a wiring
        # finding's declaration site) did not have every rule run over
        # them, so their other pragmas cannot be judged stale
        for path in sorted(analyzed):
            sf = sources[path]
            findings.extend(sf.malformed_pragmas)
            for p in sf.pragmas.values():
                if not p.used:
                    findings.append(
                        Finding(
                            "pragma", sf.path, p.line,
                            f"stale suppression: allow({', '.join(sorted(p.rules))}) "
                            "no longer matches any finding — remove it",
                        )
                    )
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))
