"""CLI: ``python -m tools.analysis [--rule NAME ...] [--changed] [paths...]``.

Prints ``path:line rule message`` per finding and exits non-zero when
anything fired. Default paths: ``lodestar_tpu/`` relative to the repo
root (so a bare ``python -m tools.analysis`` from the repo root checks
the whole tree).

``--changed`` restricts per-file rules to Python files modified vs HEAD
(plus untracked ones) under the requested paths — the pre-commit fast
path. Project-scoped rules (wiring, counted-dispatch, ...) still scan
the whole tree: their findings are global properties a single-file diff
can silently break from the other end of a reference edge.
"""

from __future__ import annotations

import argparse
import subprocess
import sys
import time
from pathlib import Path

from .core import analyze
from .rules import ALL_RULES, RULES_BY_NAME

REPO_ROOT = Path(__file__).resolve().parents[2]


def _changed_py_files(repo_root: Path) -> list[str] | None:
    """Python files changed vs HEAD plus untracked ones, as absolute
    paths; None when git is unavailable (caller falls back to full
    paths rather than silently skipping the gate)."""
    names: set[str] = set()
    for cmd in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            proc = subprocess.run(
                cmd, cwd=repo_root, capture_output=True, text=True, check=True
            )
        except (OSError, subprocess.CalledProcessError):
            return None
        names.update(ln.strip() for ln in proc.stdout.splitlines() if ln.strip())
    return [str(repo_root / n) for n in sorted(names) if n.endswith(".py")]


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.analysis",
        description="lodestar-tpu project-invariant static analysis",
    )
    ap.add_argument(
        "--rule",
        action="append",
        default=[],
        metavar="NAME",
        help="run only this rule (repeatable); default: all rules "
        "plus pragma hygiene",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    ap.add_argument(
        "--changed",
        action="store_true",
        help="per-file rules only on files changed vs HEAD (+ untracked) "
        "under the given paths; project rules still scan the whole tree",
    )
    ap.add_argument(
        "--stats",
        action="store_true",
        help="print per-rule finding counts and wall time",
    )
    ap.add_argument("paths", nargs="*", help="files or directories (default: lodestar_tpu/)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.name:24s} {r.description}")
        return 0

    rules = None
    if args.rule:
        unknown = sorted(set(args.rule) - set(RULES_BY_NAME))
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}", file=sys.stderr)
            print(f"known: {', '.join(sorted(RULES_BY_NAME))}", file=sys.stderr)
            return 2
        rules = [RULES_BY_NAME[n] for n in dict.fromkeys(args.rule)]

    paths = args.paths or [str(REPO_ROOT / "lodestar_tpu")]
    if args.changed:
        changed = _changed_py_files(REPO_ROOT)
        if changed is None:
            print(
                "--changed: git unavailable, analyzing the full paths",
                file=sys.stderr,
            )
        else:
            roots = [Path(p).resolve() for p in paths]
            paths = [
                c
                for c in changed
                if any(Path(c).resolve().is_relative_to(r) for r in roots)
            ]
            if not paths:
                print(
                    "--changed: no modified Python files under the given paths",
                    file=sys.stderr,
                )
                return 0

    stats: dict = {}
    t0 = time.monotonic()
    findings = analyze(paths, rules=rules, repo_root=REPO_ROOT, stats=stats)
    dt = time.monotonic() - t0
    for f in findings:
        print(f.format())
    if args.stats:
        for name in sorted(stats, key=lambda n: -stats[n]["seconds"]):
            s = stats[name]
            print(
                f"{name:24s} {s['findings']:4d} finding(s) {s['seconds']:7.2f}s",
                file=sys.stderr,
            )
    if args.stats or findings:
        n_rules = len(rules) if rules is not None else len(ALL_RULES)
        print(
            f"{len(findings)} finding(s) from {n_rules} rule(s) in {dt:.2f}s",
            file=sys.stderr,
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
