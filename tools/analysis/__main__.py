"""CLI: ``python -m tools.analysis [--rule NAME ...] [paths...]``.

Prints ``path:line rule message`` per finding and exits non-zero when
anything fired. Default paths: ``lodestar_tpu/`` relative to the repo
root (so a bare ``python -m tools.analysis`` from the repo root checks
the whole tree).
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

from .core import analyze
from .rules import ALL_RULES, RULES_BY_NAME

REPO_ROOT = Path(__file__).resolve().parents[2]


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.analysis",
        description="lodestar-tpu project-invariant static analysis",
    )
    ap.add_argument(
        "--rule",
        action="append",
        default=[],
        metavar="NAME",
        help="run only this rule (repeatable); default: all rules "
        "plus pragma hygiene",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    ap.add_argument(
        "--stats", action="store_true", help="print file/timing summary"
    )
    ap.add_argument("paths", nargs="*", help="files or directories (default: lodestar_tpu/)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in ALL_RULES:
            print(f"{r.name:24s} {r.description}")
        return 0

    rules = None
    if args.rule:
        unknown = sorted(set(args.rule) - set(RULES_BY_NAME))
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}", file=sys.stderr)
            print(f"known: {', '.join(sorted(RULES_BY_NAME))}", file=sys.stderr)
            return 2
        rules = [RULES_BY_NAME[n] for n in dict.fromkeys(args.rule)]

    paths = args.paths or [str(REPO_ROOT / "lodestar_tpu")]
    t0 = time.monotonic()
    findings = analyze(paths, rules=rules, repo_root=REPO_ROOT)
    dt = time.monotonic() - t0
    for f in findings:
        print(f.format())
    if args.stats or findings:
        n_rules = len(rules) if rules is not None else len(ALL_RULES)
        print(
            f"{len(findings)} finding(s) from {n_rules} rule(s) in {dt:.2f}s",
            file=sys.stderr,
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
