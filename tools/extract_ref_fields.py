"""One-time extractor: container field orders from the reference types pkg.

Parses `ContainerType({...})` declarations in
`/root/reference/packages/types/src/{phase0,altair,bellatrix,capella,deneb}/sszTypes.ts`
and writes `tests/spec/container_fields.json`: for every named container,
its camelCase field list converted to snake_case, in declaration order.

This is PARITY DATA (the consensus spec defines these field orders; the
reference merely transcribes them) — committed to the repo so the
ssz_static field-order pinning test runs without the reference checkout.

Usage: python tools/extract_ref_fields.py
"""

from __future__ import annotations

import json
import os
import re

REF = "/root/reference/packages/types/src"
OUT = os.path.join(os.path.dirname(__file__), "..", "tests", "spec", "container_fields.json")

_DECL = re.compile(
    r"(?:export )?const (\w+)\s*=\s*(new (?:ContainerType|ContainerNodeStructType)\s*\(\s*)?\{",
)
# a field line (`name: Type,`), a spread of another container's fields
# (`...phase0Ssz.BeaconBlockBody.fields,`), or a spread of a local plain
# field-dict constant (`...executionPayloadFields,`)
_ITEM = re.compile(r"^\s*(?:(\w+)\s*:|\.\.\.((?:\w+\.)*\w+)(\.fields)?\s*(?:,|$))", re.M)
_CAMEL = re.compile(r"(?<=[a-z0-9])([A-Z])")

# reference names whose trailing digit is a spec `_N` suffix (attestation_1)
# rather than part of a word (eth1_data)
_NUM_SUFFIX = {
    "attestation1": "attestation_1",
    "attestation2": "attestation_2",
    "signedHeader1": "signed_header_1",
    "signedHeader2": "signed_header_2",
    "header1": "header_1",
    "header2": "header_2",
}


def snake(name: str) -> str:
    # eth1Data -> eth1_data, blsToExecutionChanges -> bls_to_execution_changes
    if name in _NUM_SUFFIX:
        return _NUM_SUFFIX[name]
    return _CAMEL.sub(r"_\1", name).lower()


def _match_braces(src: str, open_idx: int) -> int:
    depth = 0
    for i in range(open_idx, len(src)):
        if src[i] == "{":
            depth += 1
        elif src[i] == "}":
            depth -= 1
            if depth == 0:
                return i
    raise ValueError("unbalanced braces")


def extract(path: str, resolved: dict[str, dict[str, list[str]]], fork: str) -> dict[str, list[str]]:
    with open(path) as f:
        src = f.read()
    # strip comments so commented-out fields don't match
    src = re.sub(r"/\*.*?\*/", "", src, flags=re.S)
    src = re.sub(r"//[^\n]*", "", src)
    out: dict[str, list[str]] = {}
    plain: dict[str, list[str]] = {}  # bare `const xs = {field: ...}` dicts
    for m in _DECL.finditer(src):
        name, is_container = m.group(1), bool(m.group(2))
        open_idx = m.end() - 1
        body = src[open_idx + 1 : _match_braces(src, open_idx)]
        # one item per line so single-line declarations parse too
        body = body.replace(",", ",\n")
        # JS object semantics: re-assigning an existing key overrides the
        # value but KEEPS the key's original position — exactly what dict
        # assignment does, so collect into a dict keyed by field name.
        fields_d: dict[str, None] = {}
        for fm in _ITEM.finditer(body):
            if fm.group(1):
                fields_d[snake(fm.group(1))] = None
            else:
                # resolve `forkSsz.Name.fields` / local `Name.fields` /
                # local plain dict spread `...fieldsConst`
                parts = fm.group(2).split(".")
                if parts[-1] == "fields":  # greedy match swallowed `.fields`
                    parts = parts[:-1]
                tname = parts[-1]
                src_fork = parts[0].removesuffix("Ssz") if len(parts) > 1 else fork
                base = (
                    resolved.get(src_fork, {}).get(tname)
                    or out.get(tname)
                    or plain.get(tname)
                )
                if base is None:
                    raise KeyError(f"{path}: spread of unknown {fm.group(2)}")
                for f in base:
                    fields_d[f] = None
        if not fields_d:
            continue
        (out if is_container else plain)[name] = list(fields_d)
    return out


def main() -> None:
    result: dict[str, dict[str, list[str]]] = {}
    for fork in ("phase0", "altair", "bellatrix", "capella", "deneb"):
        result[fork] = extract(os.path.join(REF, fork, "sszTypes.ts"), result, fork)
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as f:
        json.dump(result, f, indent=1, sort_keys=True)
    total = sum(len(v) for v in result.values())
    print(f"wrote {total} containers to {OUT}")


if __name__ == "__main__":
    main()
