"""BASELINE.md configs 2-5 on real hardware (VERDICT r4 next-step #10).

Prints one JSON line per config (same schema as bench.py) so the perf
notes record the SYSTEM, not just the config-1 headline:

  2. gossip replay: a per-slot ~4k-signature attestation batch pushed
     through the BlsDeviceVerifierPool (buffering, merge, RLC, retry
     policy — the production path), bls.impl = device.
  3. sync-committee aggregate: 512-pubkey fast-aggregate-verify per
     slot — device G1 tree fold + one pairing check, many slots batched.
  4. hashTreeRoot at 1M validators: the device SHA-256 merkle kernel
     over 2^20 chunks (bench.py bench_merkle, depth 20).
  5. checkpoint-backfill window: 32 slots x ~100 sigs of concurrent
     block+attestation verification as one RLC batch (single chip;
     BASELINE names v5e-4 DP — multiply by chips for the slice number,
     the sharded path is exercised by dryrun_multichip).

Also prints the HOST PREP line (native decompress+subgroup+hash-to-G2
sets/s on this container's single core) — the honest feed-rate bound
the VERDICT asks to record next to the device numbers.

Run: python tools/baseline_configs_bench.py [--quick]
"""

import json
import sys
import time

sys.path.insert(0, ".")

import numpy as np

from lodestar_tpu.utils import enable_compile_cache

enable_compile_cache(".")

QUICK = "--quick" in sys.argv
REFERENCE_SIGS_PER_SEC_PER_CORE = 2200.0  # blst envelope (bench.py)


def _line(metric, value, unit, vs, digits=1):
    print(json.dumps({
        "metric": metric, "value": round(value, digits), "unit": unit,
        "vs_baseline": round(vs, 2),
    }), flush=True)


def config2_gossip_replay(device_prep: bool = False, single_launch: bool = False):
    """Per-slot gossip attestation load through the production pool —
    one replay harness, three reported lines (same n/jobs/warm-up, so
    the comparands can't drift apart).

    With device_prep=True the whole per-set input pipeline (decompress +
    subgroup + hash-to-G2) runs on-chip (`--bls-device-prep on`); the
    prep-off run is the PERF.md r5 396.5 sigs/s baseline shape where one
    host core feeds the device. Both of those are split-schedule
    reference lines (the comparands of
    `single_launch_replay_sigs_per_sec`), so single-launch is pinned
    OFF — on a Pallas host the auto mode would otherwise route the pool
    through the one-launch program and the line would measure the
    schedule it is the reference against. With single_launch=True the
    whole verification chain of every package is ONE resident program
    (`--bls-single-launch on`; device prep stays at its ambient mode —
    the prep stages only serve that run's fault-fallback leg), reported
    as `single_launch_replay_sigs_per_sec` — the line to read against
    `gossip_replay_sigs_per_sec_device_prep`."""
    import asyncio

    from lodestar_tpu.chain.bls.interface import VerifySignatureOpts
    from lodestar_tpu.chain.bls.pool import BlsDeviceVerifierPool
    from lodestar_tpu.models.batch_verify import (
        configure_device_prep,
        configure_single_launch,
        make_synthetic_sets,
    )

    n = 1024 if QUICK else 4096
    sets = make_synthetic_sets(n, seed=31)
    opts = VerifySignatureOpts(batchable=True)

    async def run():
        pool = BlsDeviceVerifierPool()
        # warm the compiled program with one full-size merge
        jobs = [sets[i : i + 32] for i in range(0, n, 32)]
        await asyncio.gather(*[
            pool.verify_signature_sets(j, opts) for j in jobs
        ])
        t0 = time.perf_counter()
        oks = await asyncio.gather(*[
            pool.verify_signature_sets(j, opts) for j in jobs
        ])
        dt = time.perf_counter() - t0
        if not all(oks):
            raise RuntimeError("gossip replay batch failed")
        await pool.close()
        return n / dt

    prev = configure_device_prep(
        mode=None if single_launch else ("on" if device_prep else "off")
    )
    prev_single = configure_single_launch(mode="on" if single_launch else "off")
    try:
        rate = asyncio.run(run())
    finally:
        configure_single_launch(mode=prev_single)
        configure_device_prep(mode=prev)
    if single_launch:
        _line("single_launch_replay_sigs_per_sec", rate, "sigs/s",
              rate / REFERENCE_SIGS_PER_SEC_PER_CORE)
        return
    suffix = "_device_prep" if device_prep else ""
    _line(f"gossip_replay_sigs_per_sec{suffix}", rate, "sigs/s",
          rate / REFERENCE_SIGS_PER_SEC_PER_CORE)


def config3_sync_committee_aggregate():
    """512-pubkey fast-aggregate-verify per slot, slots batched."""
    import jax.numpy as jnp

    from lodestar_tpu.crypto.bls import api as bls
    from lodestar_tpu.crypto.bls.hash_to_curve import hash_to_g2
    from lodestar_tpu.ops import curve as cv, fp, pairing as prg
    from lodestar_tpu.ops import tower as tw
    from lodestar_tpu.state_transition.genesis import interop_secret_keys

    n_pk = 512
    slots = 2 if QUICK else 8
    # 512 DISTINCT keys: duplicate pubkey points would hit the P == Q
    # exceptional case in the fast (exact=False) tree fold
    sks = interop_secret_keys(n_pk)
    msg = b"\x5a" * 32
    h = hash_to_g2(msg)
    # one aggregate signature over the same message per slot
    sigs = [bls.sign(sks[i], msg) for i in range(n_pk)]
    agg_sig = bls.aggregate_signatures(sigs)
    pk_pts = [sks[i].to_pubkey_point() for i in range(n_pk)]

    # device inputs: (slots*n_pk) pubkey points -> per-slot tree fold
    pk_x = np.stack([fp.mont_limbs_from_int(p[0]) for p in pk_pts] * slots)
    pk_y = np.stack([fp.mont_limbs_from_int(p[1]) for p in pk_pts] * slots)
    h_dev = tw.fp2_from_ints([h[0]] * slots), tw.fp2_from_ints([h[1]] * slots)
    from lodestar_tpu.crypto.bls.serdes import g2_from_bytes
    sp = g2_from_bytes(agg_sig)
    sig_dev = tw.fp2_from_ints([sp[0]] * slots), tw.fp2_from_ints([sp[1]] * slots)

    import jax

    # fold per slot: vectorized tree over the pk axis
    def fold_pk_axis(X, Y, Z):
        pt = (X, Y, Z)
        while pt[0].shape[1] > 1:
            half = pt[0].shape[1] // 2
            a = tuple(c[:, :half] for c in pt)
            b = tuple(c[:, half:] for c in pt)
            pt = cv.jac_add(cv.F1, a, b, exact=False)
        return tuple(c[:, 0] for c in pt)

    @jax.jit
    def program(pk_x, pk_y, hx, hy, sx, sy):
        one1 = fp.one_mont()
        X = pk_x.reshape(slots, n_pk, fp.LIMBS)
        Y = pk_y.reshape(slots, n_pk, fp.LIMBS)
        jac = cv.affine_to_jac(cv.F1, (X, Y), one1)
        agg = fold_pk_axis(*jac)
        agg_aff = cv.jac_to_affine_batch(cv.F1, agg)
        # e(agg_pk, H(m)) * e(-g1, sig) == 1 per slot
        from lodestar_tpu.models.batch_verify import _NEG_G1_X, _NEG_G1_Y

        p_x = jnp.concatenate([agg_aff[0], jnp.broadcast_to(jnp.asarray(_NEG_G1_X), (slots, fp.LIMBS))], axis=0)
        p_y = jnp.concatenate([agg_aff[1], jnp.broadcast_to(jnp.asarray(_NEG_G1_Y), (slots, fp.LIMBS))], axis=0)
        q_x = jnp.concatenate([hx, sx], axis=0)
        q_y = jnp.concatenate([hy, sy], axis=0)
        fs = prg.miller_loop((p_x, p_y), (q_x, q_y))
        # fold pairs per slot: f_i * f_{slots+i}
        f = tw.fp12_mul(fs[:slots], fs[slots:])
        return tw.fp12_eq_one(prg.final_exponentiation(f))

    args = (jnp.asarray(pk_x), jnp.asarray(pk_y),
            jnp.asarray(np.asarray(h_dev[0])), jnp.asarray(np.asarray(h_dev[1])),
            jnp.asarray(np.asarray(sig_dev[0])), jnp.asarray(np.asarray(sig_dev[1])))
    ok = np.asarray(program(*args))
    if not ok.all():
        raise RuntimeError("fast-aggregate-verify rejected a valid aggregate")
    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        np.asarray(program(*args))
    dt = (time.perf_counter() - t0) / iters
    rate = slots / dt
    # reference envelope: one fast-aggregate-verify ~ one sig verify +
    # 511 G1 adds (~0.4ms each on blst) — conservatively ~2 ms/slot CPU
    _line("sync_committee_fast_aggregate_verifies_per_sec", rate, "slots/s", rate / 500.0)


def config4_merkle_1m():
    import bench as b

    out = b.bench_merkle(depth=18 if QUICK else 20)
    # literal metric name (asserted against bench.py's) so the bench
    # trajectory's per-line thresholds are statically checkable against
    # this module's reporting (tools/analysis bench-wiring rule)
    assert out["metric"] == "merkle_sha256_pair_hashes_per_sec", out["metric"]
    _line("merkle_sha256_pair_hashes_per_sec", out["value"], out["unit"], out["vs_baseline"])


def config5_backfill_window():
    """32-slot window: blocks (1 proposer sig each) + attestations."""
    from lodestar_tpu.models.batch_verify import (
        configure_device_prep,
        make_synthetic_sets,
        verify_signature_sets_device,
    )

    from lodestar_tpu.models import batch_verify as bv

    n = 32 * (8 if QUICK else 100)
    sets = make_synthetic_sets(n, seed=37)
    # end-to-end (host prep EVERY iteration — dominated by this host's
    # single prep core; real hosts thread the native prep). Prep is
    # PINNED to the host path so this line stays comparable to the r5
    # baseline regardless of the ambient --bls-device-prep/auto mode;
    # the prep-on delta is measured by config2's _device_prep variant.
    prev = configure_device_prep(mode="off")
    try:
        if not verify_signature_sets_device(sets):
            raise RuntimeError("backfill window rejected valid sets")
        iters = 3
        t0 = time.perf_counter()
        for _ in range(iters):
            if not verify_signature_sets_device(sets):
                raise RuntimeError("backfill window rejected valid sets")
        dt = (time.perf_counter() - t0) / iters
    finally:
        configure_device_prep(mode=prev)
    _line("backfill_window_e2e_sigs_per_sec_1core_host", n / dt, "sigs/s",
          (n / dt) / REFERENCE_SIGS_PER_SEC_PER_CORE)
    # device-only (prepared inputs reused, fresh blinding per launch —
    # the shape a threaded prep host sustains)
    inputs = bv.build_device_inputs(sets)
    pk, h, sig, bits, mask = inputs
    t0 = time.perf_counter()
    for _ in range(iters):
        fresh = bv._bits_msb(bv._random_coeffs(pk[0].shape[0]), bv.COEFF_BITS)
        if not bool(np.asarray(bv.device_batch_verify(pk, h, sig, fresh, mask))):
            raise RuntimeError("device backfill window rejected valid sets")
    dt = (time.perf_counter() - t0) / iters
    _line("backfill_window_device_sigs_per_sec", n / dt, "sigs/s",
          (n / dt) / REFERENCE_SIGS_PER_SEC_PER_CORE)


def host_prep_rate():
    from lodestar_tpu.models.batch_verify import make_synthetic_sets, prepare_sets
    from lodestar_tpu.native import bls as nbls

    n = 256
    sets = make_synthetic_sets(n, seed=41)
    prepare_sets(sets)  # warm native build
    t0 = time.perf_counter()
    out = prepare_sets(sets)
    dt = time.perf_counter() - t0
    if out is None:
        raise RuntimeError("native prep rejected valid sets")
    rate = n / dt
    _line("host_prep_sets_per_sec_single_core", rate, "sets/s",
          rate / REFERENCE_SIGS_PER_SEC_PER_CORE)
    print(json.dumps({
        "note": "container has 1 core; native prep threads scale linearly "
                "on real hosts — cores needed to feed the device at its "
                "bench rate = device_sigs_per_sec / this",
        "native_available": nbls.available(),
    }), flush=True)


def device_prep_rate():
    """On-chip input prep (ops/prep.py staged programs) sets/s — the
    apples-to-apples line next to host_prep_sets_per_sec_single_core:
    same 256-set batch, compressed bytes in, prepared device limbs out."""
    from lodestar_tpu.models.batch_verify import make_synthetic_sets, prepare_sets_device

    n = 256
    sets = make_synthetic_sets(n, seed=41)
    if prepare_sets_device(sets) is None:  # warm the staged compiles
        raise RuntimeError("device prep rejected valid sets")
    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        if prepare_sets_device(sets) is None:
            raise RuntimeError("device prep rejected valid sets")
    dt = (time.perf_counter() - t0) / iters
    rate = n / dt
    _line("device_prep_sets_per_sec", rate, "sets/s",
          rate / REFERENCE_SIGS_PER_SEC_PER_CORE)


def prep_launch_fusion():
    """Launch count before/after fusing the prep dispatch chains: the
    same batch through the pre-fusion one-launch-per-leg schedule and
    the fused stages, counted at ops/prep.py's dispatch seam (the same
    number `lodestar_bls_prep_launches_total` increments)."""
    from lodestar_tpu.models import batch_verify as bv
    from lodestar_tpu.ops import prep as dp

    n = 32
    sets = bv.make_synthetic_sets(n, seed=47)
    per_set = {}
    for fused, name in (
        (False, "prep_launches_per_set_unfused"),
        (True, "prep_launches_per_set"),
    ):
        if bv.prepare_sets_device(sets, fused=fused) is None:  # warm compiles
            raise RuntimeError("prep rejected valid sets")
        base = dp.prep_launches_total()
        if bv.prepare_sets_device(sets, fused=fused) is None:
            raise RuntimeError("prep rejected valid sets")
        per_set[name] = (dp.prep_launches_total() - base) / n
    _line(
        "prep_launches_per_set_unfused", per_set["prep_launches_per_set_unfused"],
        "launches/set", 1.0, digits=4,
    )
    _line(
        "prep_launches_per_set", per_set["prep_launches_per_set"],
        "launches/set",
        per_set["prep_launches_per_set"] / per_set["prep_launches_per_set_unfused"],
        digits=4,
    )


def single_launch_schedule():
    """End-to-end launch count per verified batch: the single-launch
    resident program (`--bls-single-launch on`, ONE counted dispatch)
    vs the split reference (3-launch fused prep + the RLC verify
    dispatch), both counted at the telemetry seam — the dispatch-budget
    invariant the chip run's launch dashboard reads."""
    from lodestar_tpu import telemetry
    from lodestar_tpu.models import batch_verify as bv

    n = 32
    sets = bv.make_synthetic_sets(n, seed=53)
    prev_tel = telemetry.configure_launch_telemetry(mode="on")
    prev_prep = bv.configure_device_prep(mode="on")
    try:
        counts = {}
        for fn, name in (
            (bv.verify_sets_single_launch, "e2e_launches_per_batch"),
            (bv._verify_sets_split, "e2e_launches_per_batch_split"),
        ):
            if not fn(sets):  # warm the compiled program(s)
                raise RuntimeError(f"{name} bench rejected valid sets")
            base = telemetry.launch_totals()["launches"]
            if not fn(sets):
                raise RuntimeError(f"{name} bench rejected valid sets")
            counts[name] = telemetry.launch_totals()["launches"] - base
    finally:
        bv.configure_device_prep(mode=prev_prep)
        telemetry.configure_launch_telemetry(mode=prev_tel)
    split = counts["e2e_launches_per_batch_split"]
    _line("e2e_launches_per_batch", counts["e2e_launches_per_batch"],
          "launches/batch", counts["e2e_launches_per_batch"] / split)
    _line("e2e_launches_per_batch_split", split, "launches/batch", 1.0)


def config2_gossip_replay_pipelined():
    """Config-2 gossip replay with the prep→verify pipeline ON (1-lane
    interleave on this container) and device prep on — the line to read
    against gossip_replay_sigs_per_sec_device_prep — plus the measured
    fraction of verify wall time with a prep stage in flight.
    Single-launch is pinned OFF like its comparand: this line measures
    the SPLIT pipeline (3-launch staged prep overlapping the verify
    dispatch), not the single-launch host-parse overlap."""
    import asyncio

    from lodestar_tpu.chain.bls.interface import VerifySignatureOpts
    from lodestar_tpu.chain.bls.pool import BlsDeviceVerifierPool
    from lodestar_tpu.models.batch_verify import (
        configure_device_prep,
        configure_single_launch,
        make_synthetic_sets,
    )

    n = 1024 if QUICK else 4096
    sets = make_synthetic_sets(n, seed=31)
    opts = VerifySignatureOpts(batchable=True)

    async def run():
        pool = BlsDeviceVerifierPool(pipeline="on")
        jobs = [sets[i : i + 32] for i in range(0, n, 32)]

        async def replay():
            # gossip is a STREAM: jobs arrive over time, so packages
            # form sequentially and prep of package k+1 runs while
            # package k verifies (an all-at-once gather coalesces the
            # whole replay into two giant packages whose preps both
            # finish before the first verify — nothing left to overlap)
            tasks = []
            for j in jobs:
                tasks.append(
                    asyncio.ensure_future(pool.verify_signature_sets(j, opts))
                )
                await asyncio.sleep(0.01)
            return await asyncio.gather(*tasks)

        await replay()  # warm the compiled programs
        base = pool.pipeline_stats()
        t0 = time.perf_counter()
        oks = await replay()
        dt = time.perf_counter() - t0
        if not all(oks):
            raise RuntimeError("pipelined gossip replay batch failed")
        stats = pool.pipeline_stats()
        await pool.close()
        if not stats["pipeline_enabled"] or stats["staged_packages"] == 0:
            raise RuntimeError(
                "pipeline never engaged — refusing to report a pipelined "
                "number for an unpipelined run"
            )
        overlap = stats["overlap_ns"] - base["overlap_ns"]
        verify = stats["verify_ns"] - base["verify_ns"]
        return n / dt, (100.0 * overlap / verify) if verify else 0.0

    prev = configure_device_prep(mode="on")
    prev_single = configure_single_launch(mode="off")
    try:
        rate, overlap_pct = asyncio.run(run())
    finally:
        configure_single_launch(mode=prev_single)
        configure_device_prep(mode=prev)
    _line("pipelined_gossip_replay_sigs_per_sec", rate, "sigs/s",
          rate / REFERENCE_SIGS_PER_SEC_PER_CORE)
    _line("prep_verify_overlap_occupancy_pct", overlap_pct, "pct",
          overlap_pct / 100.0)


def state_htr_rate():
    """Dirty-subtree collector throughput on the config-4 state shape:
    a 2^18-chunk retained level stack (2^16 with --quick) takes a
    4096-chunk dirty set per flush — the epoch-boundary balances sweep
    shape — through one device launch per level. The honest unit is
    dirty chunks *flushed* per second (path re-hash included)."""
    import numpy as np

    from lodestar_tpu.ssz import device_htr as dh

    depth = 16 if QUICK else 18
    n = 1 << depth
    rng = np.random.default_rng(51)
    chunks = rng.integers(0, 256, size=(n, 32), dtype=np.uint8)
    levels = [np.zeros((n >> k, 32), dtype=np.uint8) for k in range(depth + 1)]
    levels[0][:] = chunks
    prev = dh.configure_device_htr(mode="on")
    try:
        cold = dh.DirtyCollector()
        cold.add_stack_job(levels, range(n))
        cold.flush()  # warm the per-size-class compiles
        dirty_n = 4096
        iters = 5
        t0 = time.perf_counter()
        for it in range(iters):
            dirty = rng.choice(n, size=dirty_n, replace=False)
            levels[0][dirty] ^= np.uint8(1 + it)
            coll = dh.DirtyCollector()
            coll.add_stack_job(levels, dirty)
            stats = coll.flush()
            if stats["backend"] != "device":
                raise RuntimeError(
                    "device flush silently degraded to CPU — refusing to "
                    "report a CPU number under a device metric name"
                )
            if stats["launches"] > depth:
                raise RuntimeError("launch-count invariant violated in bench")
        dt = (time.perf_counter() - t0) / iters
    finally:
        dh.configure_device_htr(mode=prev)
    rate = dirty_n / dt
    # reference envelope: one host core does ~1M incremental pair
    # hashes/s through hashlib (BASELINE.md config 4 discussion)
    _line("state_htr_chunks_per_sec", rate, "chunks/s", rate / 1_000_000.0)


def epoch_htr_replay():
    """Epoch-boundary hashTreeRoot replay: a minimal-preset state with a
    big registry takes an epoch-shaped mutation batch (every balance
    rewritten, participation swept, a mix/slashings rotation, a handful
    of validator writes), then one state root — device collector vs the
    CPU value path, same JSON-lines shape as the prep-on/off pair."""
    import numpy as np

    from lodestar_tpu import params
    from lodestar_tpu.ssz import device_htr as dh
    from lodestar_tpu.state_transition import state_hash_tree_root
    from lodestar_tpu.types import ssz_types

    prev_preset = params.active_preset()
    params.set_active_preset("minimal")
    p = params.active_preset()
    t = ssz_types(p)
    n = 1024 if QUICK else 16384
    state = t.altair.BeaconState.default()
    vs = []
    for i in range(n):
        v = t.Validator.default()
        v.pubkey = (i.to_bytes(8, "little") * 6)[:48]
        v.effective_balance = 32_000_000_000
        v.exit_epoch = 2**64 - 1
        v.withdrawable_epoch = 2**64 - 1
        vs.append(v)
    state.validators = vs
    state.balances = [32_000_000_000] * n
    state.previous_epoch_participation = [1] * n
    state.current_epoch_participation = [3] * n
    state.inactivity_scores = [0] * n
    rng = np.random.default_rng(52)

    def epoch_mutation(round_):
        state.slot = int(state.slot) + p.SLOTS_PER_EPOCH
        state.balances = [int(x) for x in rng.integers(31_000_000_000, 33_000_000_000, size=n)]
        state.previous_epoch_participation = state.current_epoch_participation
        state.current_epoch_participation = [0] * n
        state.randao_mixes[round_ % len(state.randao_mixes)] = bytes(
            rng.integers(0, 256, size=32, dtype=np.uint8)
        )
        state.slashings[round_ % len(state.slashings)] = int(rng.integers(0, 2**40))
        for i in rng.integers(0, n, size=8):
            state.validators[int(i)].effective_balance = int(rng.integers(0, 2**40))

    # degradation probe: zero launches can be legitimate (the per-level
    # size floor keeps small levels on host digests), but a FALLBACK
    # means the device path errored and the line would silently report
    # a CPU number under a device metric name
    class _Probe:
        def __init__(self):
            self.n = 0

        def labels(self, *a):
            return self

        def inc(self, amount=1):
            self.n += amount

        def observe(self, v):
            pass

    probe = type("M", (), {})()
    for k in ("flushes", "dirty_chunks", "launches", "seconds", "fallbacks"):
        setattr(probe, k, _Probe())

    results = {}
    prev_metrics = dh._htr_metrics
    dh.configure_device_htr(metrics=probe)
    try:
        for mode, metric in (("on", "epoch_htr_ms_device"), ("off", "epoch_htr_ms_cpu")):
            prev = dh.configure_device_htr(mode=mode)
            try:
                epoch_mutation(0)
                state_hash_tree_root(state)  # warm (cold tracker build / compiles)
                iters = 3
                t0 = time.perf_counter()
                for it in range(1, iters + 1):
                    epoch_mutation(it)
                    state_hash_tree_root(state)
                results[metric] = (time.perf_counter() - t0) / iters * 1000.0
                if mode == "on" and probe.fallbacks.n:
                    raise RuntimeError(
                        "device HTR degraded during the epoch replay — "
                        "refusing to report epoch_htr_ms_device"
                    )
            finally:
                dh.configure_device_htr(mode=prev)
    finally:
        dh._htr_metrics = prev_metrics
        params.set_active_preset(prev_preset)
    cpu_ms = results["epoch_htr_ms_cpu"]
    _line("epoch_htr_ms_device", results["epoch_htr_ms_device"], "ms",
          cpu_ms / max(results["epoch_htr_ms_device"], 1e-9))
    _line("epoch_htr_ms_cpu", cpu_ms, "ms", 1.0)


def mesh_scaling():
    """`mesh_sigs_per_sec_{n}dev` for n in 1/2/4/8 ∩ visible devices:
    the same prepared batch (fresh blinding per launch, host prep
    excluded — the scaling of the VERIFY pipeline is the question)
    through the single-device program and the data-parallel sharded
    program over growing sub-meshes. On the production host this is
    the single-vs-mesh headline the PR 8 serving pool banks on; a
    1-device container emits only the 1dev line."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from lodestar_tpu.models import batch_verify as bv

    devices = jax.devices()
    counts = [n for n in (1, 2, 4, 8) if n <= len(devices)]
    n = 256 if QUICK else 1024
    sets = bv.make_synthetic_sets(n, seed=43)
    prev = bv.configure_device_prep(mode="off")
    try:
        inputs = bv.build_device_inputs(sets, size=n)
        if inputs is None:
            raise RuntimeError("mesh bench rejected valid sets")
        pk, h, sig, bits, mask = inputs
        iters = 3
        for n_dev in counts:
            if n_dev == 1:
                run = lambda b: bv.device_batch_verify(pk, h, sig, b, mask)
            else:
                mesh = Mesh(np.asarray(devices[:n_dev]), ("data",))
                run = lambda b, m=mesh: bv.device_batch_verify_sharded(
                    m, pk, h, sig, b, mask
                )
            if not bool(np.asarray(run(bits))):  # warm the compile
                raise RuntimeError(f"mesh bench rejected valid sets at {n_dev} devices")
            t0 = time.perf_counter()
            for _ in range(iters):
                fresh = bv._bits_msb(bv._random_coeffs(n), bv.COEFF_BITS)
                if not bool(np.asarray(run(fresh))):
                    raise RuntimeError(
                        f"mesh bench rejected valid sets at {n_dev} devices"
                    )
            dt = (time.perf_counter() - t0) / iters
            _line(f"mesh_sigs_per_sec_{n_dev}dev", n / dt, "sigs/s",
                  (n / dt) / REFERENCE_SIGS_PER_SEC_PER_CORE)
    finally:
        bv.configure_device_prep(mode=prev)


def two_tenant_fairness_replay():
    """Saturated two-tenant replay against the offload front-end:
    tenants alice (weight 3) and bob (weight 1) over-admit bulk work
    against one service slot; the line reports the worst deviation of
    served shares from the configured 75/25 split, in percentage
    points (acceptance envelope: 10). The backend is a fixed 2 ms stub
    — service time is a parameter here; the MEASUREMENT is the stride
    scheduler's cross-tenant fairness, which is what the serving host
    runs regardless of die speed."""
    import asyncio
    import threading

    from lodestar_tpu.offload.client import BlsOffloadClient
    from lodestar_tpu.offload.server import BlsOffloadServer

    def backend(sets):
        time.sleep(0.002)
        return True

    server = BlsOffloadServer(
        backend, port=0, max_workers=8,
        tenant_weights={"alice": 3, "bob": 1}, tenant_slots=1,
    )
    server.start()
    target = f"127.0.0.1:{server.port}"
    from lodestar_tpu.models.batch_verify import make_synthetic_sets

    job = make_synthetic_sets(4, seed=44)
    clients = {
        name: BlsOffloadClient(target, probe_interval_s=0.05, tenant=name)
        for name in ("alice", "bob")
    }
    try:
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if all(
                s["tenant_capable"]
                for c in clients.values()
                for s in c.endpoint_states()
            ):
                break
            time.sleep(0.02)

        async def go():
            stop = asyncio.Event()
            from lodestar_tpu.chain.bls.interface import VerifySignatureOpts
            from lodestar_tpu.scheduler import PriorityClass

            bulk = VerifySignatureOpts(priority=PriorityClass.BACKFILL)

            async def pump(client):
                while not stop.is_set():
                    try:
                        await client.verify_signature_sets(job, bulk)
                    except Exception:
                        await asyncio.sleep(0.001)

            pumps = [
                asyncio.ensure_future(pump(c))
                for c in clients.values()
                for _ in range(8)
            ]
            while not all(
                server.tenancy.served.get(t, 0) > 0 for t in ("alice", "bob")
            ):
                await asyncio.sleep(0.01)
            base = {t: server.tenancy.served.get(t, 0) for t in ("alice", "bob")}
            target_grants = 150 if QUICK else 600
            while True:
                window = {
                    t: server.tenancy.served.get(t, 0) - base[t]
                    for t in ("alice", "bob")
                }
                if sum(window.values()) >= target_grants:
                    break
                await asyncio.sleep(0.02)
            stop.set()
            await asyncio.gather(*pumps, return_exceptions=True)
            return window

        window = asyncio.run(go())
        total = sum(window.values())
        err_pct = 100.0 * max(
            abs(window["alice"] / total - 0.75), abs(window["bob"] / total - 0.25)
        )
        # vs_baseline: fraction of the 10-point acceptance envelope used
        _line("two_tenant_fairness_share_error_pct", err_pct, "pct", err_pct / 10.0)
    finally:
        for c in clients.values():
            asyncio.run(c.close())
        server.stop()


def main():
    host_prep_rate()
    device_prep_rate()
    prep_launch_fusion()
    config4_merkle_1m()
    state_htr_rate()
    epoch_htr_replay()
    config5_backfill_window()
    single_launch_schedule()
    config2_gossip_replay()
    config2_gossip_replay(device_prep=True)
    config2_gossip_replay(single_launch=True)
    config2_gossip_replay_pipelined()
    config3_sync_committee_aggregate()
    mesh_scaling()
    two_tenant_fairness_replay()


if __name__ == "__main__":
    main()
