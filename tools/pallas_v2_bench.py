"""Pallas iteration-2 experiments on the real device.

A: mont_mul kernel WITHOUT the exact-carry/borrow canonicalization tail
   (loose <2p output) — isolates the unrolled-column tail cost.
B: EIGHT chained lazy mont_muls inside ONE kernel (all intermediates in
   VMEM) — measures the cross-op fusion payoff that would justify
   building fused fp2/fp6/fp12 Pallas ops.
Baselines: XLA mont_mul chain-8, pallas v1 single.
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lodestar_tpu.ops import fp, fp_pallas
from lodestar_tpu.utils import enable_compile_cache

enable_compile_cache(".")

B = int(sys.argv[1]) if len(sys.argv) > 1 else 4096 * 54
K = int(sys.argv[2]) if len(sys.argv) > 2 else 64
BLOCK = fp_pallas.BLOCK

_PP = [int(v) for v in fp.PPRIME_LIMBS]
_PL = [int(v) for v in fp.P_LIMBS]


def _lazy_mont_body(pad_ref, a, b):
    """One lazy mont_mul on (BLOCK, 32) VMEM arrays -> loose (<2p)."""
    zeros_pad = jnp.zeros((BLOCK, 128), jnp.int32)

    def load(x32):
        pad_ref[:] = zeros_pad
        pad_ref[:, 64:96] = x32

    def carry(x, width):
        c = x >> 12
        lo = x & 0xFFF
        pad_ref[:] = zeros_pad
        pad_ref[:, 64 : 64 + width] = c
        return lo + pad_ref[:, 63 : 63 + width]

    acc = jnp.zeros((BLOCK, 64), jnp.int32)
    load(a)
    for j in range(32):
        acc = acc + pad_ref[:, 64 - j : 128 - j] * b[:, j : j + 1]
    for _ in range(3):
        acc = carry(acc, 64)
    m = jnp.zeros((BLOCK, 32), jnp.int32)
    load(acc[:, :32])
    for j in range(32):
        if _PP[j]:
            m = m + pad_ref[:, 64 - j : 96 - j] * _PP[j]
    for _ in range(3):
        m = carry(m, 32)
    s = acc
    load(m)
    for j in range(32):
        if _PL[j]:
            s = s + pad_ref[:, 64 - j : 128 - j] * _PL[j]
    for _ in range(3):
        s = carry(s, 64)
    cbit = jnp.any(s[:, :32] != 0, axis=-1, keepdims=True).astype(jnp.int32)
    hi = s[:, 32:]
    return jnp.concatenate([hi[:, :1] + cbit, hi[:, 1:]], axis=-1)


def _kernel_lazy1(a_ref, b_ref, o_ref, pad_ref):
    o_ref[:] = _lazy_mont_body(pad_ref, a_ref[:], b_ref[:])


def _kernel_lazy8(a_ref, b_ref, o_ref, pad_ref):
    x = a_ref[:]
    b = b_ref[:]
    for _ in range(8):
        x = _lazy_mont_body(pad_ref, x, b)
    o_ref[:] = x


def _call(kernel, a, b):
    n = a.shape[0]
    spec = pl.BlockSpec((BLOCK, 32), lambda i: (i, 0), memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, 32), jnp.int32),
        grid=(n // BLOCK,),
        in_specs=[spec, spec],
        out_specs=spec,
        scratch_shapes=[pltpu.VMEM((BLOCK, 128), jnp.int32)],
    )(a, b)


rng = np.random.default_rng(0)
vals = lambda n: [int.from_bytes(rng.bytes(47), "big") % fp.P for _ in range(n)]
n_pad = (B + BLOCK - 1) // BLOCK * BLOCK
a = jnp.asarray(np.vstack([fp.limbs_from_ints(vals(B)), np.zeros((n_pad - B, 32), np.int32)]))
b = jnp.asarray(np.vstack([fp.limbs_from_ints(vals(B)), np.zeros((n_pad - B, 32), np.int32)]))


def bench(name, fn, iters=3, per_call_ops=1):
    @jax.jit
    def f(x, y):
        out = x
        for _ in range(K // per_call_ops):
            out = fn(out, y)
        return out[0, :1]

    np.asarray(f(a, b))
    t0 = time.perf_counter()
    for _ in range(iters):
        np.asarray(f(a, b))
    dt = (time.perf_counter() - t0) / iters / K
    print(f"{name:36s} {dt*1e3:8.3f} ms/mont_mul", flush=True)


bench("XLA mont_mul (canonical)", fp.mont_mul)
bench("pallas v1 (canonical)", lambda x, y: fp_pallas._mont_mul_flat(x, y))
bench("pallas v2A lazy single", lambda x, y: _call(_kernel_lazy1, x, y))
bench("pallas v2B lazy chain-8 in-kernel", lambda x, y: _call(_kernel_lazy8, x, y), per_call_ops=8)
print("done", flush=True)
