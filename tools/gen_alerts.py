"""Generate the Prometheus alert rules under alerts/ (multi-window
multi-burn-rate SLO alerts over the lodestar_slo_* SLI pairs, plus the
deadline/slack and standing health alerts).

The committed file is `alerts/lodestar_alerts.yml` — JSON content
(JSON is a YAML subset, so promtool/Prometheus load it unmodified)
written with sort_keys so regeneration is byte-stable; the
regen-is-noop test and `--check` diff it exactly, the same doctrine as
tools/gen_dashboards.py.

Every expr is validated AT GENERATION TIME against the statically
collected metric registry (the same Family/sample-name derivation the
`metrics-and-cli-wiring` and `alert-wiring` analysis rules use:
counters surface as <name>_total, histograms as _bucket/_sum/_count) —
an alert naming a sample no family can expose is a generation error,
not a silently-dead rule.

Burn-rate windows follow the multi-window multi-burn-rate recipe: a
page fires only when BOTH a short and a long window burn the error
budget at 14.4x (fast: 5m + 1h — budget gone in ~2 days), a ticket at
6x (slow: 30m + 6h — gone in ~5 days). The short window makes the
alert reset quickly once the burn stops; the long window keeps a brief
blip from paging.

Run from the repo root: python tools/gen_alerts.py  [--check]
"""

import argparse
import json
import os
import sys

OUT = "alerts"
RULES_FILE = "lodestar_alerts.yml"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: SLO availability target for the verification SLI (good verdicts
#: inside the class deadline / total verdicts): 99.9% → an error
#: budget of 0.1% of jobs per window
SLO_TARGET = 0.999
ERROR_BUDGET = 1.0 - SLO_TARGET

#: (tier, short window, long window, burn-rate factor, severity)
BURN_WINDOWS = (
    ("fast", "5m", "1h", 14.4, "page"),
    ("slow", "30m", "6h", 6.0, "ticket"),
)


def _error_ratio(window: str) -> str:
    """Per-class SLI error ratio over `window`: 1 - good/total, grouped
    by class so the firing alert names WHICH deadline class burns."""
    return (
        "(1 - (sum by (class) (rate(lodestar_slo_sli_good_total[{w}])) "
        "/ sum by (class) (rate(lodestar_slo_sli_total[{w}]))))"
    ).format(w=window)


def burn_rate_rules():
    rules = []
    for tier, short, long_, factor, severity in BURN_WINDOWS:
        threshold = round(factor * ERROR_BUDGET, 6)
        rules.append(
            {
                "alert": f"LodestarSloBurnRate{tier.capitalize()}",
                "expr": (
                    f"{_error_ratio(short)} > {threshold} and "
                    f"{_error_ratio(long_)} > {threshold}"
                ),
                "for": "2m" if tier == "fast" else "15m",
                "labels": {"severity": severity, "slo": "verify-deadline"},
                "annotations": {
                    "summary": (
                        f"{tier} burn: class {{{{ $labels.class }}}} is "
                        f"burning the {SLO_TARGET:.1%} verify-deadline "
                        f"error budget at >{factor}x over both {short} "
                        f"and {long_} windows"
                    ),
                    "runbook": (
                        "check the slack dashboard (lodestar_slo.json): "
                        "which wait-budget leg grew — buffer/queue legs "
                        "point at admission or batch-former pressure, "
                        "launch leg at device/compile trouble"
                    ),
                },
            }
        )
    return rules


def deadline_rules():
    return [
        {
            # gossip blocks missing the attestation cutoff is the
            # highest-stakes miss the node can produce: page on ANY
            # sustained rate
            "alert": "LodestarGossipBlockDeadlineMiss",
            "expr": (
                'sum(rate(lodestar_slo_deadline_miss_total'
                '{class="gossip_block"}[5m])) > 0'
            ),
            "for": "2m",
            "labels": {"severity": "page", "slo": "verify-deadline"},
            "annotations": {
                "summary": (
                    "gossip-block verifications are missing the 1/3-slot "
                    "attestation cutoff (sustained over 5m)"
                ),
                "runbook": (
                    "GET /eth/v0/debug/slo for the per-class wait-budget "
                    "decomposition; slow-slot dumps carry per-class slack "
                    "at dump time"
                ),
            },
        },
        {
            "alert": "LodestarDeadlineMissElevated",
            "expr": (
                "sum by (class) "
                "(rate(lodestar_slo_deadline_miss_total[30m])) > 0.1"
            ),
            "for": "15m",
            "labels": {"severity": "ticket", "slo": "verify-deadline"},
            "annotations": {
                "summary": (
                    "class {{ $labels.class }} misses its slot deadline "
                    ">0.1/s over 30m"
                ),
                "runbook": "read the slack histogram by stage: slack already "
                "negative at enqueue means upstream (gossip/sync) delivery "
                "is late, slack lost between dispatch and verdict means the "
                "verify path is slow",
            },
        },
        {
            # leading indicator: the fraction of verdicts landing with
            # slack already negative (le="0.0" bucket of the slack
            # histogram) — fires before the SLI pair degrades enough to
            # burn budget
            "alert": "LodestarSlackExhausted",
            "expr": (
                'sum by (class) (rate(lodestar_slo_slack_seconds_bucket'
                '{le="0.0",stage="verdict"}[10m])) / sum by (class) '
                "(rate(lodestar_slo_slack_seconds_count"
                '{stage="verdict"}[10m])) > 0.05'
            ),
            "for": "10m",
            "labels": {"severity": "ticket", "slo": "verify-deadline"},
            "annotations": {
                "summary": (
                    ">5% of class {{ $labels.class }} verdicts land with "
                    "zero or negative deadline slack"
                ),
                "runbook": "compare the enqueue-stage slack histogram: if "
                "enqueue slack is healthy the budget is being spent inside "
                "this process (wait-budget profiler names the leg)",
            },
        },
    ]


def health_rules():
    """Standing health alerts over the pre-SLO families: the conditions
    an operator already watches on the dashboards, promoted to rules."""
    return [
        {
            "alert": "LodestarOffloadBreakerOpen",
            "expr": "max by (endpoint) (lodestar_resilience_breaker_state) == 2",
            "for": "5m",
            "labels": {"severity": "ticket"},
            "annotations": {
                "summary": (
                    "offload endpoint {{ $labels.endpoint }} breaker open "
                    "for 5m — verifications are riding the fallback chain"
                ),
                "runbook": "lodestar_offload_resilience.json: failover and "
                "degradation-chain panels",
            },
        },
        {
            "alert": "LodestarMeshLanesExhausted",
            "expr": "lodestar_sched_mesh_lanes_available == 0",
            "for": "5m",
            "labels": {"severity": "page"},
            "annotations": {
                "summary": "no non-wedged mesh lanes for 5m — every verify "
                "chip is wedged or breaker-tripped",
                "runbook": "lodestar_mesh_serving.json: per-chip wedge trips",
            },
        },
        {
            "alert": "LodestarEventLoopLagHigh",
            "expr": (
                "histogram_quantile(0.95, "
                "rate(lodestar_event_loop_lag_seconds_bucket[5m])) > 0.5"
            ),
            "for": "10m",
            "labels": {"severity": "ticket"},
            "annotations": {
                "summary": "event-loop scheduling lag p95 >500ms — loop "
                "starvation will show up as buffer/queue wait in the SLO "
                "decomposition",
                "runbook": "lodestar_node_internals.json: event loop panel",
            },
        },
        {
            "alert": "LodestarSlowSlotStorm",
            "expr": "rate(lodestar_trace_slow_slot_total[10m]) > 0.05",
            "for": "10m",
            "labels": {"severity": "ticket"},
            "annotations": {
                "summary": "slow-slot dumps firing >3/min over 10m",
                "runbook": "read the exported dumps — each names its device "
                "launches and per-class deadline slack inline",
            },
        },
    ]


def alert_doc():
    return {
        "groups": [
            {"name": "lodestar-slo-burn-rate", "rules": burn_rate_rules()},
            {"name": "lodestar-slo-deadline", "rules": deadline_rules()},
            {"name": "lodestar-health", "rules": health_rules()},
        ]
    }


def validate_against_registry(doc) -> list:
    """Every metric-shaped token in every alert expr must be a sample
    name derivable from a registered family — the generation-time twin
    of the alert-wiring analysis rule."""
    from tools.analysis.rules.wiring import (
        _GROUP_CLAUSE_RE,
        _LABEL_SELECTOR_RE,
        _PROMQL_WORDS,
        _TOKEN_RE,
        collect_metric_families,
    )
    from pathlib import Path

    fams = collect_metric_families(Path(REPO) / "lodestar_tpu")
    samples = set()
    for fam in fams:
        samples.update(fam.samples())
    errors = []
    for group in doc["groups"]:
        for rule in group["rules"]:
            expr = _LABEL_SELECTOR_RE.sub("", rule["expr"])
            expr = _GROUP_CLAUSE_RE.sub("", expr)
            for tok in _TOKEN_RE.findall(expr):
                if "_" in tok and tok not in _PROMQL_WORDS and tok not in samples:
                    errors.append(f"{rule['alert']}: unknown sample '{tok}'")
    return errors


def render() -> str:
    doc = alert_doc()
    errors = validate_against_registry(doc)
    if errors:
        raise SystemExit("gen_alerts: exprs name unregistered samples:\n  " + "\n  ".join(errors))
    # sort_keys keeps the output byte-stable across dict-build order
    # changes, so --check and the regen-is-noop test can diff exactly
    return json.dumps(doc, indent=2, sort_keys=True) + "\n"


def main(out: str = OUT, check: bool = False) -> int:
    text = render()
    path = os.path.join(out, RULES_FILE)
    if check:
        try:
            with open(path) as f:
                committed = f.read()
        except OSError:
            print(f"{path} missing — run: python tools/gen_alerts.py")
            return 1
        if committed != text:
            print(f"{path} is stale — run: python tools/gen_alerts.py")
            return 1
        return 0
    os.makedirs(out, exist_ok=True)
    with open(path, "w") as f:
        f.write(text)
    print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--check", action="store_true",
        help="diff against the committed rules instead of writing (exit 1 on drift)",
    )
    ap.add_argument("--out", default=OUT)
    args = ap.parse_args()
    raise SystemExit(main(out=args.out, check=args.check))
