"""Which dtype/layout does this TPU actually execute fast?

Same chained-op harness as kernel_microbench (sync on scalar pull), but
over raw elementwise candidates: int32 vs float32 vs bfloat16 mul/add,
shift-based carries vs float floor carries, minor-dim 32 vs 128, and a
bf16 MXU matmul for scale.
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

from lodestar_tpu.utils import enable_compile_cache

enable_compile_cache(".")

B = int(sys.argv[1]) if len(sys.argv) > 1 else 4096 * 54
K = int(sys.argv[2]) if len(sys.argv) > 2 else 16

rng = np.random.default_rng(0)
ai32 = jnp.asarray(rng.integers(0, 4096, size=(B, 32), dtype=np.int32))
bi32 = jnp.asarray(rng.integers(0, 4096, size=(B, 32), dtype=np.int32))
af32 = ai32.astype(jnp.float32)
bf32 = bi32.astype(jnp.float32)
abf16 = ai32.astype(jnp.bfloat16)
bbf16 = bi32.astype(jnp.bfloat16)
ai32w = jnp.asarray(rng.integers(0, 4096, size=(B, 128), dtype=np.int32))
af32w = ai32w.astype(jnp.float32)


def timeit(name, f, *args, bytes_per_call=None, iters=3):
    g = jax.jit(f)
    np.asarray(g(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = np.asarray(g(*args))
    dt = (time.perf_counter() - t0) / iters / K
    gbps = (bytes_per_call or 0) / dt / 1e9
    print(f"{name:40s} {dt*1e3:9.3f} ms/call {gbps:8.1f} GB/s", flush=True)


ARR32 = B * 32 * 4
ARR128 = B * 128 * 4


def chain(op, x, y):
    for _ in range(K):
        x = op(x, y)
    return x[0, :1]


timeit("int32 mul+add (B,32)", lambda x, y: chain(lambda a, b: a * b + a, x, y), ai32, bi32, bytes_per_call=3 * ARR32)
timeit("float32 mul+add (B,32)", lambda x, y: chain(lambda a, b: a * b + a, x, y), af32, bf32, bytes_per_call=3 * ARR32)
timeit("bf16 mul+add (B,32)", lambda x, y: chain(lambda a, b: a * b + a, x, y), abf16, bbf16, bytes_per_call=3 * ARR32 // 2)
timeit("int32 add only (B,32)", lambda x, y: chain(lambda a, b: a + b, x, y), ai32, bi32, bytes_per_call=3 * ARR32)
timeit("int32 shift+mask (B,32)", lambda x, y: chain(lambda a, b: (a >> 12) + (b & 0xFFF), x, y), ai32, bi32, bytes_per_call=3 * ARR32)
timeit("f32 floor-carry (B,32)", lambda x, y: chain(lambda a, b: a - jnp.floor(a * (1 / 4096)) * 4096 + b, x, y), af32, bf32, bytes_per_call=3 * ARR32)
timeit("int32 mul+add (B,128)", lambda x, y: chain(lambda a, b: a * b + a, x, y), ai32w, ai32w, bytes_per_call=3 * ARR128)
timeit("f32 mul+add (B,128)", lambda x, y: chain(lambda a, b: a * b + a, x, y), af32w, af32w, bytes_per_call=3 * ARR128)

# conv via shifted FMAs in f32 at (B,64) out
def conv_f32(a, b):
    total = None
    for j in range(32):
        term = jnp.pad(a * b[:, j : j + 1], [(0, 0), (j, 32 - j)])
        total = term if total is None else total + term
    return total


timeit("conv shifted-FMA f32", lambda x, y: chain(lambda a, b: conv_f32(a, b)[:, :32], x, y), af32, bf32, bytes_per_call=4 * ARR32)

# bf16 matmul for scale: (B, 48) @ (48, 96)
w = jnp.asarray(rng.integers(0, 256, size=(48, 96), dtype=np.int32)).astype(jnp.bfloat16)
x48 = jnp.asarray(rng.integers(0, 256, size=(B, 48), dtype=np.int32)).astype(jnp.bfloat16)


def mm(a, _):
    return jnp.dot(a, w, preferred_element_type=jnp.float32).astype(jnp.bfloat16)


def mm_chain(x, y):
    for _ in range(K):
        x = mm(x, y)[:, :48]
    return x[0, :1].astype(jnp.float32)


timeit("bf16 MXU matmul (B,48)@(48,96)", mm_chain, x48, x48, bytes_per_call=int(1.5 * B * 48 * 2))

print("done", flush=True)
