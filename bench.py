"""Benchmark entrypoint (driver-run on real TPU hardware).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Headline: the NORTH STAR (BASELINE.md config 1) — random-linear-combination
BLS batch verification throughput on a 128-set batch, the workload the
reference routes to its blst thread pool
(`packages/beacon-node/src/chain/bls/multithread/worker.ts:30`,
`verifyMultipleAggregateSignatures`). The device pipeline is
`lodestar_tpu.models.batch_verify`: blinded G1/G2 scalar muls, 129 Miller
loops in lockstep, one shared final exponentiation.

vs_baseline: the reference envelope is ~45 ms for ~100 single-core blst
signature verifications (`verifyBlocksSignatures.ts:41-43`) ≈ 2,200 sigs/s
per core. vs_baseline = device_sigs_per_sec / 2200 — i.e. "how many blst
cores does one TPU chip replace"; ≥10 meets the north-star target.

A secondary line for the SHA-256 merkle kernel is retained in
`bench_merkle()` (BASELINE config 4) for comparison runs but the driver
reads only the first printed line.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

REFERENCE_SIGS_PER_SEC_PER_CORE = 2200.0  # blst envelope, see module docstring
BATCH = 128  # sets per gossip job (the north-star workload unit)
# buffered jobs merged into one RLC device batch. Swept on the real
# v5e-1 with the r5 Pallas core: 16 -> 6781 sigs/s, 32 -> 6586,
# 64 -> 5353 (PERF.md) — the r4 knee of 32 moved to 16 with the faster
# program. Overridable for batch-width sweeps.
MERGE_JOBS = int(os.environ.get("LODESTAR_BENCH_MERGE_JOBS", "16"))
ITERS = 3


def _make_sets(n: int):
    from lodestar_tpu.models.batch_verify import make_synthetic_sets

    return make_synthetic_sets(n, seed=17)


def bench_batch_verify() -> dict:
    """Sustained verification throughput of 128-set gossip jobs.

    The verifier pool buffers batchable jobs and merges them into one
    random-linear-combination batch (the reference merges buffered gossip
    sets the same way, `maybeBatch.ts:18`; we merge MERGE_JOBS x 128 =
    1024 sets per launch). The program is latency-bound, so widening the
    merged batch multiplies throughput at near-constant wall time —
    measured: 8 sets -> 13 sigs/s, 128 -> 216, 1024 -> see BENCH_r03.
    """
    from lodestar_tpu.models import batch_verify as bv

    sets = _make_sets(BATCH)
    inputs = bv.build_device_inputs(sets)
    assert inputs is not None
    pk, h, sig, bits, mask = inputs

    # merge MERGE_JOBS buffered jobs into one device batch: tile the
    # prepared arrays (distinct jobs in production; identical content is
    # fine for throughput — each copy gets fresh blinding)
    def tile1(a):
        return np.concatenate([a] * MERGE_JOBS, axis=0)

    merged = BATCH * MERGE_JOBS
    pk_m = (tile1(pk[0]), tile1(pk[1]))
    h_m = (tile1(h[0]), tile1(h[1]))
    sig_m = (tile1(sig[0]), tile1(sig[1]))
    mask_m = np.ones(merged, dtype=bool)

    def fresh_bits():
        coeffs = bv._random_coeffs(merged)
        return bv._bits_msb(coeffs, bv.COEFF_BITS)

    # warmup + compile; correctness gate on the first run
    ok = bool(np.asarray(bv.device_batch_verify(pk_m, h_m, sig_m, fresh_bits(), mask_m)))
    assert ok, "warmup merged batch failed to verify"

    # steady state: fresh blinding per launch, same compiled program;
    # dispatch all launches then drain (the 1-byte result transfer is the
    # sync point — block_until_ready is unreliable through the axon relay)
    jobs = [fresh_bits() for _ in range(ITERS)]
    t0 = time.perf_counter()
    results = [bv.device_batch_verify(pk_m, h_m, sig_m, b, mask_m) for b in jobs]
    oks = [bool(np.asarray(r)) for r in results]
    dt = (time.perf_counter() - t0) / ITERS
    assert all(oks)

    sigs_per_sec = merged / dt
    return {
        "metric": "bls_batch_verify_sigs_per_sec",
        "value": round(sigs_per_sec, 1),
        "unit": "sigs/s",
        "vs_baseline": round(sigs_per_sec / REFERENCE_SIGS_PER_SEC_PER_CORE, 2),
    }


def bench_merkle(depth: int = 20) -> dict:
    """Secondary: batched SHA-256 merkleization (BASELINE config 4)."""
    import hashlib

    import jax

    from lodestar_tpu.ops import sha256 as S

    n = 1 << depth
    rng = np.random.default_rng(0)
    chunks_np = rng.integers(0, 2**32, size=(n, 8), dtype=np.uint32)
    chunks = jax.device_put(chunks_np)
    np.asarray(S.merkle_root_device(chunks))

    iters = 5
    t0 = time.perf_counter()
    roots = [S.merkle_root_device(chunks) for _ in range(iters)]
    for r in roots:
        np.asarray(r)
    dt = (time.perf_counter() - t0) / iters
    device_rate = (n - 1) / dt

    sample = 1 << 14
    data = chunks_np[: 2 * sample].astype(">u4").tobytes()
    t0 = time.perf_counter()
    for i in range(sample):
        hashlib.sha256(data[i * 64 : (i + 1) * 64]).digest()
    cpu_rate = sample / (time.perf_counter() - t0)

    return {
        "metric": "merkle_sha256_pair_hashes_per_sec",
        "value": round(device_rate),
        "unit": "hashes/s",
        "vs_baseline": round(device_rate / cpu_rate, 2),
    }


def main() -> None:
    import os

    from lodestar_tpu.utils import enable_compile_cache

    enable_compile_cache(os.path.dirname(os.path.abspath(__file__)))
    print(json.dumps(bench_batch_verify()))


if __name__ == "__main__":
    main()
