"""Benchmark entrypoint (driver-run on real TPU hardware).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Current headline: batched SHA-256 merkleization throughput (BASELINE
config 4 — the `hashTreeRoot(BeaconState)` hot loop, reference
`packages/state-transition/src/stateTransition.ts:100` via
`@chainsafe/persistent-merkle-tree` + as-sha256). vs_baseline is the ratio
against the host hashlib path measured in the same run — the stand-in for
the reference's WASM as-sha256 single-thread hasher.

When the BLS device pipeline lands this switches to aggregate sigs/sec
(north-star metric, BASELINE config 1/2).
"""

from __future__ import annotations

import hashlib
import json
import time

import numpy as np


def _bench_merkle(depth: int = 20) -> dict:
    import jax

    from lodestar_tpu.ops import sha256 as S

    n = 1 << depth
    rng = np.random.default_rng(0)
    chunks_np = rng.integers(0, 2**32, size=(n, 8), dtype=np.uint32)
    chunks = jax.device_put(chunks_np)

    # warmup/compile all level shapes; synchronize via host transfer of the
    # 32-byte root — block_until_ready() is a no-op through the axon relay,
    # so transfers are the only trustworthy sync point
    np.asarray(S.merkle_root_device(chunks))

    # dispatch all iterations first (pipelined, as production batches would
    # be), then drain: the device executes in order, so total time is
    # compute-bound with a single 32-byte D2H per tree
    iters = 5
    t0 = time.perf_counter()
    roots = [S.merkle_root_device(chunks) for _ in range(iters)]
    for r in roots:
        np.asarray(r)
    dt = (time.perf_counter() - t0) / iters
    n_hashes = n - 1  # pair-hashes in a complete binary tree
    device_rate = n_hashes / dt

    # host baseline: hashlib pair-hash rate on a sample, extrapolated
    sample = 1 << 14
    data = chunks_np[: 2 * sample].astype(">u4").tobytes()
    t0 = time.perf_counter()
    for i in range(sample):
        hashlib.sha256(data[i * 64 : (i + 1) * 64]).digest()
    cpu_dt = time.perf_counter() - t0
    cpu_rate = sample / cpu_dt

    return {
        "metric": "merkle_sha256_pair_hashes_per_sec",
        "value": round(device_rate),
        "unit": "hashes/s",
        "vs_baseline": round(device_rate / cpu_rate, 2),
    }


def main() -> None:
    result = _bench_merkle()
    print(json.dumps(result))


if __name__ == "__main__":
    main()
